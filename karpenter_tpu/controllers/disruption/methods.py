"""Disruption methods, tried in order — first success wins.

Mirror of the reference's method set (disruption/controller.go:80-91):
Drift → Emptiness → EmptyNodeConsolidation → MultiNodeConsolidation →
SingleNodeConsolidation. Consolidation shares `compute_consolidation`
(consolidation.go:112-296): simulate, require every displaced pod to
schedule, allow at most one replacement node, and apply the price filter
(the replacement must be launchable strictly cheaper than what the
candidates currently cost; spot→spot additionally requires the feature gate
and ≥15 cheaper types to prevent churn).
"""

from __future__ import annotations

from karpenter_tpu import obs
from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.nodeclaim import COND_DRIFTED, COND_EMPTY
from karpenter_tpu.api.nodepool import (
    CONSOLIDATION_WHEN_EMPTY,
    CONSOLIDATION_WHEN_UNDERUTILIZED,
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_INTERRUPTED,
    REASON_UNDERUTILIZED,
)
from karpenter_tpu.controllers.disruption.helpers import (
    simulate_scheduling,
    within_budget,
)
from karpenter_tpu.controllers.disruption.types import Command

MULTI_NODE_CANDIDATE_CAP = 100  # multinodeconsolidation.go:82
SPOT_TO_SPOT_MIN_TYPES = 15  # consolidation.go:253-277
MULTI_NODE_TIMEOUT = 60.0  # multinodeconsolidation.go:37
SINGLE_NODE_TIMEOUT = 180.0  # singlenodeconsolidation.go:46


class Method:
    reason: str = ""
    needs_validation: bool = False
    # consolidation methods honor the isConsolidated fence: skipped while
    # cluster state is unchanged since the last fruitless search
    is_consolidation: bool = False
    # the decision-ledger site whose verdict shipped this method's
    # commands — the fleet ledger stamps it on every command's cause
    # chain (obs/timeline.py); empty for methods without a ladder site
    decision_site: str = ""

    def __init__(self, ctx):
        self.ctx = ctx  # DisruptionContext: provisioner, cluster, store, clock, options

    def compute_command(self, candidates, budgets) -> Command | None:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


def _claim_condition(candidate, cond) -> bool:
    claim = candidate.state_node.node_claim
    return claim is not None and claim.is_true(cond)


class Drift(Method):
    """Replace nodes whose claims carry the Drifted condition
    (disruption/drift.go:35-115)."""

    reason = REASON_DRIFTED

    def compute_command(self, candidates, budgets):
        drifted = [c for c in candidates if _claim_condition(c, COND_DRIFTED)]
        drifted.sort(
            key=lambda c: (
                c.state_node.node_claim.get_condition(COND_DRIFTED).last_transition_time
            )
        )
        drifted = within_budget(budgets, self.reason, drifted)
        if not drifted:
            return None
        # empty drifted candidates can all go at once, no simulation
        empty = [c for c in drifted if not c.reschedulable_pods]
        if empty:
            return Command(empty, reason=self.reason)
        # else one at a time, with replacement simulation (sharing the
        # round's cached solver inputs when still generation-current)
        ctx = self.ctx
        cache = getattr(ctx, "snapshot_cache", None)
        bundle = (
            cache.refresh(ctx.provisioner, ctx.cluster, ctx.store,
                          registry=ctx.registry)
            if cache is not None else None
        )
        inputs = cache.inputs_for(ctx.cluster) if cache is not None else None
        for c in drifted:
            with obs.span("confirm.simulate", method="drift"):
                sim = simulate_scheduling(
                    self.ctx.provisioner, self.ctx.cluster, self.ctx.store,
                    [c], inputs=inputs, bundle=bundle,
                )
            if not sim.all_pods_scheduled():
                continue
            return Command([c], replacements=sim.new_claims, reason=self.reason)
        return None


class Emptiness(Method):
    """Delete nodes empty for ≥ consolidateAfter under WhenEmpty
    (disruption/emptiness.go:32-85). No simulation."""

    reason = REASON_EMPTY

    def compute_command(self, candidates, budgets):
        clock = self.ctx.clock
        empty = []
        for c in candidates:
            if c.node_pool.spec.disruption.consolidation_policy != CONSOLIDATION_WHEN_EMPTY:
                continue
            claim = c.state_node.node_claim
            if claim is None or not claim.is_true(COND_EMPTY):
                continue
            if c.reschedulable_pods:
                continue
            wait = c.node_pool.spec.disruption.consolidate_after or 0.0
            cond = claim.get_condition(COND_EMPTY)
            since = cond.last_transition_time if cond is not None else None
            if since is None:
                # condition present but its transition time unset (partial
                # status write, wire-doc normalization gap): the age gate
                # cannot be proven, so the node is NOT yet eligible — skip
                # it this round instead of raising mid-ladder
                continue
            if clock.now() - since < wait:
                continue
            empty.append(c)
        empty = within_budget(budgets, self.reason, empty)
        if not empty:
            return None
        return Command(empty, reason=self.reason)


class InterruptionDrain(Method):
    """Proactive spot drain-and-replace (deploy/README.md "Spot
    resilience"). An interruption notice marks the node on cluster state
    (``Cluster.note_interruption``, pulled from the cloud provider by the
    disruption controller); this method — ordered before every
    consolidation method, because a reclaim deadline outranks any
    voluntary optimization — ships ONE command per notice-bearing round:

    * **proactive** (the top rung): the replacement is solved off the
      round's cached :class:`DisruptionSnapshot` — one counterfactual row
      on the existing probe/dispatch seam (recorded under the
      ``interruption.dispatch`` replay-capsule seam) asks whether the
      SURVIVORS absorb every displaced pod, then the confirming
      ``simulate_scheduling`` sizes the actual replacement claims — and
      because ``needs_validation`` is False the command executes this
      round: replacements launch immediately, the orchestration queue
      holds the candidate-claim deletion until every replacement is
      Initialized, and only then does the PDB-gated drain wave ship.
      A notice with ≥1 round of lead therefore never loses a pod to the
      reclaim — the zero-late-drain acceptance ``python -m perf spot``
      and ``bench.py --spot`` gate on.
    * **degraded**: a deadline already inside
      ``KARPENTER_INTERRUPTION_MIN_LEAD`` (30 s) — or one that arrives
      MID-SOLVE (the simulation outran the clock) — degrades gracefully
      to an immediate drain with no replacement wait: salvaging part of
      the workload beats wedging the round against a dead deadline.
    * **reactive**: the replacement solve cannot place the pods (no
      capacity); the node drains bare and the provisioner's
      deleting-node pre-provisioning rescues what it can post-drain.

    Interruption is INVOLUNTARY disruption: budgets are not consulted
    (the capacity is leaving either way) and nodes the candidate filters
    exclude (do-not-disrupt, PDB-blocked) are still drained — a blocked
    eviction retries until the deadline kills the node, which is the
    cloud's doing, not ours. Every round records one
    ``disrupt.interruption`` decision-ledger verdict (closed enums,
    obs/decisions.py)."""

    reason = REASON_INTERRUPTED
    needs_validation = False  # a validation TTL would eat the deadline
    decision_site = "disrupt.interruption"
    last_rung: str = ""  # "proactive" | "reactive" | "degraded" (tests)

    @property
    def uses_bundle(self) -> bool:
        """Ask the controller to prewarm the round's snapshot ONLY when a
        live notice exists: the absorb probe rides the bundle, but a
        notice-free round must not pay a fleet tensorization for a method
        that returns None immediately."""
        cluster = getattr(self.ctx, "cluster", None)
        if cluster is None:
            return False
        return any(sn.interruption_pending()
                   for sn in cluster.state_nodes())

    def _verdict(self, rung, reason="ok"):
        from karpenter_tpu.obs import decisions

        self.last_rung = rung
        decisions.record_decision("disrupt.interruption", rung, reason,
                                  registry=self.ctx.registry)

    def compute_command(self, candidates, budgets):
        self.last_rung = ""
        noticed = self._noticed(candidates)
        if not noticed:
            return None
        ctx = self.ctx
        from karpenter_tpu.operator import metrics as m
        from karpenter_tpu.utils.envknobs import env_float

        min_lead = env_float("KARPENTER_INTERRUPTION_MIN_LEAD", 30.0,
                             minimum=0.0)
        # PARTITION by lead, never aggregate: one short-lead notice must
        # not degrade nodes whose deadlines still leave room for the
        # proactive replace — the urgent subset drains NOW (most urgent
        # first wins the round) and the with-lead rest rides the next
        # poll, still far inside its lead
        urgent = [c for dl, c in noticed
                  if ctx.clock.now() + min_lead > dl]
        if urgent:
            return self._degrade(urgent)
        deadline = min(dl for dl, _ in noticed)
        cands = [c for _, c in noticed]
        absorbed = self._absorb_probe(cands)
        if absorbed:
            # the device row says the SURVIVORS absorb every displaced pod
            # with zero fresh claims: ship the delete-only drain without
            # paying the host simulation — the fastest possible path on a
            # ticking deadline. The probe can only OVER-estimate (f32 fit):
            # a wrong "absorbed" leaves pods pending post-drain and the
            # provisioner re-provisions them next round — the reactive
            # path's behavior, never a wedge or a loss.
            self._verdict("proactive", "delete-only")
            ctx.registry.counter(
                m.INTERRUPTION_PROACTIVE_DRAINS,
                "interruption-noticed nodes drained proactively "
                "(replacement launched-and-ready before the drain wave)",
            ).inc(len(cands))
            return Command(cands, reason=self.reason)
        cache = getattr(ctx, "snapshot_cache", None)
        bundle = (
            cache.refresh(ctx.provisioner, ctx.cluster, ctx.store,
                          registry=ctx.registry)
            if cache is not None else None
        )
        inputs = cache.inputs_for(ctx.cluster) if cache is not None else None
        with obs.span("confirm.simulate", method="interruption",
                      noticed=len(cands), absorbed=absorbed):
            sim = simulate_scheduling(
                ctx.provisioner, ctx.cluster, ctx.store, cands,
                inputs=inputs, bundle=bundle,
            )
        if ctx.clock.now() + min_lead > deadline:
            # a deadline arrived mid-solve: shipping a replacement wait
            # now would outlive that capacity — degrade the now-urgent
            # subset instead of wedging (the rest retries next poll)
            urgent = [c for dl, c in noticed
                      if ctx.clock.now() + min_lead > dl]
            return self._degrade(urgent or cands)
        if not sim.all_pods_scheduled():
            self._verdict("reactive", "reactive-fallback")
            return Command(cands, reason=self.reason)
        self._verdict("proactive",
                      "ok" if sim.new_claims else "delete-only")
        ctx.registry.counter(
            m.INTERRUPTION_PROACTIVE_DRAINS,
            "interruption-noticed nodes drained proactively (replacement "
            "launched-and-ready before the drain wave)",
        ).inc(len(cands))
        return Command(cands, replacements=sim.new_claims,
                       reason=self.reason)

    def _degrade(self, cands):
        from karpenter_tpu.operator import metrics as m

        self._verdict("degraded", "deadline-degraded")
        self.ctx.registry.counter(
            m.INTERRUPTION_DEADLINE_DEGRADATIONS,
            "interruption notices whose deadline forced the immediate-"
            "drain degradation (no replacement wait)",
        ).inc(len(cands))
        return Command(cands, reason=self.reason)

    def _noticed(self, candidates):
        """[(deadline, Candidate)] for every live noticed node, soonest
        first. Candidates the controller's filters excluded
        (do-not-disrupt, PDB) are rebuilt directly — an interruption
        ignores voluntary-disruption gates."""
        ctx = self.ctx
        if getattr(ctx, "cluster", None) is None:
            return []
        by_pid = {c.provider_id: c for c in candidates}
        out = []
        view = None
        for sn in list(ctx.cluster.state_nodes()):
            if not sn.interruption_pending():
                continue
            dl = sn.interruption_deadline
            c = by_pid.get(sn.provider_id)
            if c is None:
                if view is None:
                    from karpenter_tpu.cloudprovider.types import CatalogView

                    view = CatalogView(ctx.store.list("nodepools"),
                                       ctx.cloud)
                c = self._make_candidate(sn, view)
                if c is None:
                    continue
            out.append((dl, c))
        out.sort(key=lambda t: t[0])
        return out

    def _make_candidate(self, sn, view):
        from karpenter_tpu.controllers.disruption.types import Candidate

        labels = sn.labels()
        np_ = view.pool_of(labels)
        if np_ is None:
            return None
        it = (view.instance_type(labels)
              if getattr(self.ctx, "cloud", None) is not None else None)
        return Candidate(sn.snapshot(), np_, it, self.ctx.clock)

    def _absorb_probe(self, cands):
        """One counterfactual row on the cached bundle's dispatch seam:
        do the SURVIVING nodes absorb every noticed node's pods with zero
        fresh claims? ``True`` short-circuits the host simulation (a
        delete-only drain ships immediately — the over-estimate direction
        degrades to the provisioner rescue, see compute_command);
        ``False``/``None`` hands the decision to the simulation. Recorded
        under the ``interruption.dispatch`` capsule seam so an anomalous
        storm round replays offline. None when the bundle cannot express
        the query — probe failures must never block an interruption
        drain."""
        import numpy as np

        ctx = self.ctx
        cache = getattr(ctx, "snapshot_cache", None)
        bundle = cache.current(ctx.cluster) if cache is not None else None
        if bundle is None:
            return None
        try:
            cols = bundle.columns_for(cands)
            if cols is None:
                return None
            contrib = bundle.contribs_for(cands, cols=cols)
            if contrib is None:
                return None
            need = contrib.sum(axis=0)
            row = (bundle.base + need)[None, :]
            with obs.span("interruption.probe", candidates=len(cands)):
                placed_g, used = bundle.dispatch(
                    row, [np.asarray(cols, dtype=np.intp)],
                    seam="interruption.dispatch")
            G = bundle.snap.G
            return bool((placed_g[0, :G] >= need).all()
                        and int(used[0]) == 0)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "interruption absorb probe failed; the confirming "
                "simulation decides alone", exc_info=True)
            return None


def _consolidatable(candidates):
    out = []
    for c in candidates:
        d = c.node_pool.spec.disruption
        if d.consolidation_policy != CONSOLIDATION_WHEN_UNDERUTILIZED:
            continue
        out.append(c)
    return out


def _candidate_order(ctx, pool):
    """ONE disruption-cost order shared by GlobalConsolidation,
    MultiNodeConsolidation, and SingleNodeConsolidation — sharing it is
    what lets the joint dispatch's seed answer the per-candidate probes
    (ops/consolidate.py ``JointSeed`` aligns by pid sequence, and a
    method sorting differently would decline every seed).

    Primary key: ``disruption_cost`` (the reference's order). Secondary
    key (ISSUE 14 — the first slice of the PR-11/PR-13 priority lever):
    on EXACT cost ties only, prefer retiring the candidate displacing
    lower-tier pods — effective priority per admission/priority.py's
    apiserver matrix, keyed ``(max tier, summed tier)`` over the node's
    reschedulable pods. The sort is stable and a priority-free fleet
    resolves every tier key to ``(0, 0)``, so end states without
    priorities are bit-identical to the plain cost sort. Tier
    resolution is paid only for the candidates actually inside a tie
    run, and the whole order is memoized per (generation, pool) on the
    DisruptionContext — all three methods order the SAME candidate
    objects within one round, so the second and third calls are a tuple
    compare."""
    cluster = getattr(ctx, "cluster", None)
    key = None
    if cluster is not None:
        key = (cluster.consolidation_state(),
               tuple(c.provider_id for c in pool))
        memo = getattr(ctx, "order_memo", None)
        if memo is not None and memo[0] == key:
            return list(memo[1])
    out = _compute_candidate_order(ctx, pool)
    if key is not None:
        ctx.order_memo = (key, out)
    return list(out)


def _compute_candidate_order(ctx, pool):
    pool = sorted(pool, key=lambda c: c.disruption_cost)
    if len(pool) < 2 or getattr(ctx, "store", None) is None:
        return pool
    # tie runs of equal cost: only their members pay tier resolution
    runs = []
    i = 0
    while i < len(pool):
        j = i + 1
        while j < len(pool) and (
                pool[j].disruption_cost == pool[i].disruption_cost):
            j += 1
        if j - i > 1:
            runs.append((i, j))
        i = j
    if not runs:
        return pool  # all distinct: the tie-break can never reorder
    from karpenter_tpu.admission.priority import (
        default_class,
        resolve_priority,
    )

    classes = {
        pc.metadata.name: pc for pc in ctx.store.list("priorityclasses")
    }
    dflt = default_class(classes)

    def tier_key(c):
        prios = [
            resolve_priority(p, classes, dflt)[0]
            for p in c.reschedulable_pods
        ]
        return (max(prios, default=0), sum(prios))

    # a stable per-run re-sort is exactly the global (cost, tier) sort:
    # runs are maximal equal-cost spans, so keys never cross runs
    for i, j in runs:
        pool[i:j] = sorted(pool[i:j], key=tier_key)
    return pool


def _seed_answer(ctx, cands, kind):
    """The joint dispatch's seed answer for a per-candidate probe
    (ops/consolidate.py ``JointSeed``), or None: the seed must be from
    the SAME cluster-state generation (any state bump invalidates it)
    and the querying method's candidate list must be a prefix of the
    seeded pool in the shared order. Records nothing — the caller
    records the probe.confirm verdict with reason ``joint-seeded``."""
    seed = getattr(ctx, "joint_seed", None)
    if seed is None or not seed.valid(getattr(ctx, "cluster", None)):
        return None
    pids = tuple(c.provider_id for c in cands)
    if kind == "prefix":
        return seed.prefix_answer(pids)
    return seed.single_answer(pids)


class EmptyNodeConsolidation(Method):
    """Bulk-delete empty nodes under WhenUnderutilized
    (disruption/emptynodeconsolidation.go:30-115)."""

    reason = REASON_EMPTY
    needs_validation = True
    is_consolidation = True

    def compute_command(self, candidates, budgets):
        empty = [c for c in _consolidatable(candidates) if not c.reschedulable_pods]
        empty = within_budget(budgets, self.reason, empty)
        if not empty:
            return None
        return Command(empty, reason=self.reason)


def candidate_prices(candidates) -> float | None:
    """Sum of the candidates' current offering prices, or None when ANY
    candidate's price is unknown (delisted offering, price <= 0) — the
    reference's getCandidatePrices error stance (consolidation.go:86-97):
    an unpriceable node cannot anchor a "strictly cheaper" comparison, and
    silently summing it as 0 understates the current cost, letting a
    replacement pass against a candidate set it may not actually beat."""
    total = 0.0
    for c in candidates:
        p = c.price
        if p <= 0:
            return None
        total += p
    return total


def predicted_command_savings(cmd) -> float | None:
    """Criterion-predicted savings RATE of a command at execution time:
    the candidates' summed effective price minus the cheapest effective
    offering each replacement claim can still launch as — the number the
    fleet ledger reconciles against realized savings when the command
    completes (obs/timeline.py; deploy/README.md "Fleet ledger"). None
    when either side is unpriceable (the :func:`candidate_prices`
    stance: an unknown price cannot anchor a reconciliation)."""
    current = candidate_prices(cmd.candidates)
    if current is None:
        return None
    from karpenter_tpu.cloudprovider.types import effective_price, risk_lambda

    lam = risk_lambda()  # hoisted: one env read, not one per offering
    replacement = 0.0
    for claim in cmd.replacements:
        best = None
        for it in claim.instance_types:
            for o in it.offerings.available().compatible(claim.requirements):
                p = effective_price(o, lam)
                if p > 0 and (best is None or p < best):
                    best = p
        if best is None:
            return None
        replacement += best
    return current - replacement


def compute_consolidation(ctx, candidates) -> Command | None:
    """Shared consolidation core (consolidation.go:112-296)."""
    cache = getattr(ctx, "snapshot_cache", None)
    bundle = (
        cache.refresh(ctx.provisioner, ctx.cluster, ctx.store,
                      registry=ctx.registry)
        if cache is not None else None
    )
    inputs = cache.inputs_for(ctx.cluster) if cache is not None else None
    sim = simulate_scheduling(
        ctx.provisioner, ctx.cluster, ctx.store, candidates, inputs=inputs,
        bundle=bundle,
    )
    if not sim.all_pods_scheduled():
        return None
    if len(sim.new_claims) == 0:
        return Command(candidates, reason=REASON_UNDERUTILIZED)
    if len(sim.new_claims) > 1:
        return None  # m→1 replacement only (consolidation.go:164)

    replacement = sim.new_claims[0]
    current_price = candidate_prices(candidates)
    if current_price is None:
        return None  # unpriceable candidate: abort the replacement path
    all_spot = all(c.capacity_type == wk.CAPACITY_TYPE_SPOT for c in candidates)

    # the replacement must launch strictly cheaper than the candidates cost
    # now: filter its instance types to the cheaper-than-current set
    # (consolidation.go filterByPrice:210), keeping the comparison price
    # (spot-only when the whole candidate set is spot). Both sides of the
    # comparison are risk-discounted EFFECTIVE prices (candidate_prices
    # reads Candidate.price, which already is), so with λ > 0 a
    # consolidation only ships when the replacement is cheaper risk
    # included — a nominally-cheap high-risk offering does not buy churn
    from karpenter_tpu.cloudprovider.types import effective_price, risk_lambda
    lam = risk_lambda()  # hoisted: one env read, not one per offering
    priced = []
    for it in replacement.instance_types:
        ofs = it.offerings.available().compatible(replacement.requirements)
        if all_spot:
            # spot→spot: compare within spot offerings only
            ofs = type(ofs)(o for o in ofs if o.capacity_type == wk.CAPACITY_TYPE_SPOT)
        if not ofs:
            continue
        p = min(effective_price(o, lam) for o in ofs)
        if p < current_price:
            priced.append((p, it))
    if not priced:
        return None

    if all_spot:
        if not ctx.options.get("spot_to_spot_consolidation", False):
            return None  # feature-gated (consolidation.go:214)
        if len(candidates) == 1 and len(priced) < SPOT_TO_SPOT_MIN_TYPES:
            return None  # anti-churn floor (consolidation.go:253-277)
        # keep the CHEAPEST 15 by the same SPOT-ONLY price the filter used
        # (the reference price-sorts its options before slicing,
        # consolidation.go:269): launching from the cheapest spot band is
        # the whole point of the churn — an on-demand offering priced
        # under a type's spot price must not buy it a slot
        priced.sort(key=lambda t: (t[0], t[1].name))
        priced = priced[:SPOT_TO_SPOT_MIN_TYPES]
    # else: on-demand (or mixed) candidates keep both capacity types and
    # the full cheaper set, in the replacement's original (price) order

    replacement.instance_types = [it for _, it in priced]
    return Command(candidates, replacements=[replacement], reason=REASON_UNDERUTILIZED)


def confirm_consolidation(ctx, cands, method_label, **span_attrs):
    """ONE real simulation of a candidate set, with the same-type price
    filter applied to any replacement — the confirm contract shared
    verbatim by the MultiNode prefix and the global joint set (ONE copy,
    so the "identical confirm contract" guarantee cannot drift; the
    unknown-price stance rides inside: candidate_prices aborting the
    replace path in compute_consolidation keeps commands delete-only
    whenever a candidate cannot be priced). None = the set fails."""
    from karpenter_tpu.operator import metrics as m

    ctx.registry.counter(
        m.DISRUPTION_HOST_CONFIRMS,
        "confirming host simulations run by consolidation methods",
    ).inc(method=method_label)
    with obs.span("confirm.simulate", method=method_label, **span_attrs), \
            ctx.registry.measure(m.DISRUPTION_CONFIRM_DURATION,
                                 method=method_label):
        cmd = compute_consolidation(ctx, cands)
    if cmd is None or cmd.action == "no-op":
        return None
    if cmd.action == "replace":
        kept = filter_out_same_type(cmd.replacements[0], cands)
        if not kept:
            return None
        cmd.replacements[0].instance_types = kept
    return cmd


def filter_out_same_type(replacement, candidates) -> list:
    """Price-sanity filter for m→1 replacements
    (multinodeconsolidation.go:181-215): when the replacement's instance-type
    options include a type we are deleting, drop every option that is not
    strictly cheaper than the cheapest such overlapping node — otherwise the
    "consolidation" would relaunch one of its own victims, which is just a
    delete with extra churn. All comparisons run on risk-discounted
    EFFECTIVE prices (Candidate.price and effective_price; nominal at λ=0).

    A same-type candidate with UNKNOWN price (delisted offering, price <= 0)
    cannot anchor the strictly-cheaper comparison directly. The original
    ADVICE.md round-5 stance dropped its type from the options outright
    (delete-only direction). Under λ > 0 that blanket stance narrows (the
    round-5 gap close): when the delisted candidate's type still has an
    available, priced offering of the OTHER capacity type whose risk is
    KNOWN, that offering's effective price anchors the comparison instead
    — pricing the same-type spot↔on-demand move the old stance forbade.
    A type with no such risk-known cross-capacity offering — or any
    λ=0 deployment (the anchor is λ-gated so the risk-blind default is
    bit-identical to pre-ISSUE-15 behavior) — keeps the conservative
    delete-only treatment: we still never buy what we can't price."""
    existing_prices: dict = {}
    unknown_types: set = set()
    for c in candidates:
        if c.instance_type is None:
            continue
        p = c.price
        if p <= 0:
            anchor = _cross_capacity_anchor(c)
            if anchor is None:
                unknown_types.add(c.instance_type.name)
                continue
            p = anchor
        prev = existing_prices.get(c.instance_type.name)
        if prev is None or p < prev:
            existing_prices[c.instance_type.name] = p
    # a type is unpriceable only when NO candidate of it has a known price:
    # a mixed type (one delisted node, one priced node) keeps both its
    # anchor and its option slot — the priced node bounds the comparison
    unknown_types -= set(existing_prices)
    options = [
        it for it in replacement.instance_types if it.name not in unknown_types
    ]
    max_price = float("inf")
    for it in replacement.instance_types:
        if it.name in existing_prices:
            max_price = min(max_price, existing_prices[it.name])
    if max_price == float("inf"):
        return options
    from karpenter_tpu.cloudprovider.types import effective_price, risk_lambda

    lam = risk_lambda()  # hoisted: one env read, not one per offering
    kept = []
    for it in options:
        ofs = it.offerings.available().compatible(replacement.requirements)
        if ofs and min(effective_price(o, lam) for o in ofs) < max_price:
            kept.append(it)
    return kept


def _cross_capacity_anchor(c) -> float | None:
    """Effective price anchoring an unpriceable candidate's same-type
    comparison through the OTHER capacity type: the cheapest available,
    priced offering of ``c.instance_type`` in a different capacity type
    whose ``interruption_risk`` is KNOWN (not None). None = no such
    offering, keep the delete-only stance (filter_out_same_type).

    Gated on λ > 0: the anchor only engages once the operator has opted
    into risk-discounted economics, so the default λ=0 deployment keeps
    the pre-ISSUE-15 delete-only behavior EXACTLY (the λ=0 bit-parity
    acceptance covers behavior, not just the price tensors)."""
    from karpenter_tpu.cloudprovider.types import effective_price, risk_lambda

    lam = risk_lambda()
    if lam <= 0.0 or c.instance_type is None:
        return None
    ct = getattr(c, "capacity_type", "")
    best = None
    for o in c.instance_type.offerings.available():
        if o.capacity_type == ct or o.price <= 0:
            continue
        if o.interruption_risk is None:
            continue  # unknown risk: cannot vouch for the move
        p = effective_price(o, lam)
        if best is None or p < best:
            best = p
    return best


def _probe_failure(ctx, method_label, site):
    """ONE copy of the probe-failure diagnosis (counter + anomaly +
    sequential verdict), shared by the per-candidate probes and the
    global joint solve so the two rungs cannot drift on how a dying
    probe is diagnosed. Falling back is by design (the probes are
    prefilters), but the reason must stay diagnosable — a permanently-
    failing probe silently costs every consolidation round its batched
    dispatch; the counter makes it visible on the scrape. Callers keep
    their WARNING (with the traceback) inline in the except handler —
    stdlib logging is never configured here, only WARNING+ reaches the
    lastResort stderr handler (the models/solver.py precedent), and
    GL303 wants the log visibly in the handler."""
    from karpenter_tpu.obs import decisions
    from karpenter_tpu.operator import metrics as m

    ctx.registry.counter(
        m.DISRUPTION_PROBE_FAILURES,
        "device consolidation probes that fell back to the "
        "sequential search",
    ).inc(method=method_label)
    # anomaly trigger: a fallback costs the round its batched dispatch
    # — the flight recorder keeps this round's span tree so the
    # failing stage is attributable from the dump, not just counted
    obs.anomaly("probe-fallback", registry=ctx.registry,
                method=method_label)
    decisions.record_decision(site, "sequential", "probe-error",
                              registry=ctx.registry)


def _device_probe(ctx, probe_fn, method_label, cands, pool):
    """Shared probe runner for both per-candidate consolidation methods:
    the TPUSolver gate, the exception fallback (`_probe_failure`), and
    the batch-size histogram."""
    from karpenter_tpu.models.solver import TPUSolver
    from karpenter_tpu.obs import decisions

    if not isinstance(getattr(ctx.provisioner, "solver", None), TPUSolver):
        decisions.record_decision("probe.confirm", "sequential", "no-device",
                                  registry=ctx.registry)
        return None
    try:
        with obs.span("probe", method=method_label, candidates=len(cands)):
            out = probe_fn(
                ctx.provisioner, ctx.cluster, ctx.store, cands,
                cache=getattr(ctx, "snapshot_cache", None),
                registry=ctx.registry,
                # the snapshot is built over the FULL consolidatable pool so
                # MultiNode's and SingleNode's probes share one tensorization
                build_candidates=pool,
            )
    except Exception:
        import logging

        _probe_failure(ctx, method_label, "probe.confirm")
        logging.getLogger(__name__).warning(
            "device consolidation probe (%s) failed; using the sequential "
            "search", method_label, exc_info=True)
        return None
    if out is not None:
        from karpenter_tpu.operator import metrics as m

        ctx.registry.histogram(
            m.DISRUPTION_PROBE_BATCH_SIZE,
            "counterfactual rows ranked per batched probe dispatch",
            buckets=m.PROBE_BATCH_BUCKETS,
        ).observe(len(cands), method=method_label)
    else:
        # the probe could not express the scenario (no bundle, invisible
        # candidate, unmapped pods): the method runs the reference search
        decisions.record_decision("probe.confirm", "sequential",
                                  "inexpressible", registry=ctx.registry)
    return out


# sentinel distinguishing a scan the wall clock cut short from one that
# exhausted (and thereby CLEARED) its candidates — the single-node
# back-check must never treat "never checked" as "rejected"
_TIMED_OUT = object()


def _search_timed_out(ctx, deadline, search_type) -> bool:
    """Wall-clock budget check shared by both consolidation searches
    (multinodeconsolidation.go:37, singlenodeconsolidation.go:46)."""
    if ctx.clock.now() <= deadline:
        return False
    from karpenter_tpu.operator import metrics as m

    ctx.registry.counter(
        m.CONSOLIDATION_TIMEOUTS, "consolidation searches cut off by wall clock"
    ).inc(type=search_type)
    return True


def _global_enabled() -> bool:
    from karpenter_tpu.utils.envknobs import env_bool

    return env_bool("KARPENTER_GLOBAL_CONSOLIDATION", True)


def _global_cap() -> int:
    from karpenter_tpu.utils.envknobs import env_int

    return env_int("KARPENTER_GLOBAL_CAP", GLOBAL_CANDIDATE_CAP, minimum=2)


# joint-ladder row ceiling: far above any real fleet (the 2k config is the
# headline), it only bounds the counterfactual row count a pathological
# candidate list could enqueue in one dispatch
GLOBAL_CANDIDATE_CAP = 4096

# fleets at or below this size always carry the single-candidate rows in
# the joint dispatch (they're near-free there); larger fleets carry them
# only after a noop verdict armed the hint or the bundle is
# mid-transition — a fresh underutilized fleet's first dispatch (which
# almost surely ships a command) skips ~N wasted rows
GLOBAL_SINGLES_MAX = 256


class GlobalConsolidation(Method):
    """Global consolidation: ONE joint device solve over ALL candidates
    proposes the whole retirement set plus its displacement plan, and
    exactly one confirming simulation validates the winning set before
    the command ships (deploy/README.md "Global consolidation").

    The per-candidate ladder below (MultiNode prefix search + SingleNode
    scan) is greedy by construction — each round retires one command's
    worth of nodes and waits for the next generation. Here every prefix
    of the SAME disruption-cost order is a counterfactual row of one
    batched dispatch (ops/consolidate.py ``joint_retirement_plan``), a
    host rounding/repair pass makes the winning row integral, and the
    whole 2k-node underutilized fleet collapses in one command instead of
    a generation-paced descent. The ladder is retired to ORACLE duty:
    topology-compiled bundles, inexpressible shapes, non-definitive
    ladders (the seed under-estimates and needs MultiNode's gallop),
    repair overflows,
    and probe-vs-host confirm disagreements all fall through to it (this
    method returns None and the method order does the rest), so the
    shipped end state is never worse than the reference's. Every
    resolution records one ``consolidate.global`` ledger verdict
    (obs/decisions.py): joint/ok when the set ships, the ladder rung with
    its fallback cause otherwise, sequential when no device solve ran at
    all. ``KARPENTER_GLOBAL_CONSOLIDATION=0`` disables the mode (the
    ladder then owns every round, exactly the pre-ISSUE-13 behavior)."""

    reason = REASON_UNDERUTILIZED
    needs_validation = True
    is_consolidation = True
    uses_bundle = True  # the controller prewarms the round's snapshot
    decision_site = "consolidate.global"
    last_rung: str = ""  # "joint" | "ladder" | "sequential" (tests + perf)
    last_plan = None  # the round's JointPlan (tests + observability)
    # when True the controller closes the consolidation round after this
    # method returns None: the joint dispatch PROVED round-wide
    # no-retirement (every prefix and every single candidate infeasible,
    # misses definitive) on a mid-transition snapshot — running the
    # MultiNode/SingleNode probes would re-pay dispatches to learn
    # nothing (deploy/README.md "Global consolidation", short-circuit)
    fence_round: bool = False
    # singles hint: armed after the method's FIRST dispatch-bearing round
    # of the process. Every round after a ship or a noop is near-certain
    # to answer no-retirement (the fleet was just consolidated, or
    # already judged packed), and carrying the single rows lets that
    # round seed or fence the whole ladder off its one dispatch — only
    # the cold first solve of a process (the classic underutilized fleet
    # that ships immediately) skips the ~N extra rows
    _singles_armed: bool = False

    def _verdict(self, rung, reason="ok"):
        from karpenter_tpu.obs import decisions

        self.last_rung = rung
        decisions.record_decision("consolidate.global", rung, reason,
                                  registry=self.ctx.registry)

    def compute_command(self, candidates, budgets):
        self.last_plan = None
        self.fence_round = False
        if not _global_enabled():
            self._verdict("sequential", "disabled")
            return None
        pool = _candidate_order(self.ctx, _consolidatable(candidates))
        allowed = within_budget(budgets, self.reason, pool)
        cands = allowed[:_global_cap()]
        # whether the joint dispatch saw EVERY budget-allowed candidate:
        # a cap-truncated view can seed the capped MultiNode question but
        # must never claim round-wide no-retirement (SingleNode's scan is
        # uncapped, and candidates beyond the cap were never examined)
        pool_complete = len(cands) == len(allowed)
        if len(cands) < 2:
            self._verdict("sequential", "too-few-candidates")
            return None
        from karpenter_tpu.models.solver import TPUSolver

        if not isinstance(getattr(self.ctx.provisioner, "solver", None),
                          TPUSolver):
            self._verdict("sequential", "no-device")
            return None
        try:
            from karpenter_tpu.ops.consolidate import joint_retirement_plan

            with obs.span("global.probe", candidates=len(cands)):
                plan = joint_retirement_plan(
                    self.ctx.provisioner, self.ctx.cluster, self.ctx.store,
                    cands,
                    cache=getattr(self.ctx, "snapshot_cache", None),
                    registry=self.ctx.registry,
                    build_candidates=pool,
                    # singles hint: any round after the process's first
                    # dispatch (or on small fleets, where the rows are
                    # near-free) carries the per-candidate rows so the
                    # verdict can seed/fence SingleNode too;
                    # mid-transition bundles force them regardless
                    # (joint_retirement_plan)
                    want_singles=(self._singles_armed
                                  or len(cands) <= GLOBAL_SINGLES_MAX),
                )
        except Exception:
            import logging

            # _probe_failure records the sequential verdict itself (the
            # shared diagnosis path — counter, anomaly, verdict)
            self.last_rung = "sequential"
            _probe_failure(self.ctx, "global", "consolidate.global")
            logging.getLogger(__name__).warning(
                "device consolidation probe (%s) failed; using the "
                "sequential search", "global", exc_info=True)
            return None
        self.last_plan = plan
        if plan is None:
            self._verdict("sequential", "inexpressible")
            return None
        if plan.prefix_feasible is not None:
            self._singles_armed = True
            # publish the dispatch's answers as the round's seed: the
            # MultiNode/SingleNode probes below answer off it instead of
            # re-paying a device dispatch for the same generation
            from karpenter_tpu.ops.consolidate import JointSeed

            self.ctx.joint_seed = JointSeed(
                plan.generation,
                [c.provider_id for c in cands],
                plan.prefix_feasible,
                plan.definitive,
                plan.single_mask,
            )
        if plan.timings.get("solve_ms") is not None:
            # rows were actually ranked (the dispatch ran — viable or
            # not), mirroring _device_probe's any-non-None stance
            from karpenter_tpu.operator import metrics as m

            self.ctx.registry.histogram(
                m.DISRUPTION_PROBE_BATCH_SIZE,
                "counterfactual rows ranked per batched probe dispatch",
                buckets=m.PROBE_BATCH_BUCKETS,
            ).observe(len(cands), method="global")
        if not plan.viable:
            if (plan.transient and plan.reason == "no-retirement"
                    and plan.definitive and pool_complete
                    and plan.single_mask is not None
                    and not plan.single_mask.any()):
                # provable round-wide noop off the one dispatch, on a
                # MID-TRANSITION snapshot (pending or drain-in-flight
                # pods): every prefix AND every single candidate is
                # infeasible with definitive misses, so the ladder below
                # could only re-learn it — close the round. The next
                # state bump (the wave is still moving) re-probes; a
                # SETTLED fleet's noop verdict deliberately does NOT
                # fence, so the ladder's seeded descent still pays its
                # paranoia confirms against the probe's residual f32
                # false-negative corner — zero extra dispatches either
                # way.
                self._verdict("joint", "joint-noop-fenced")
                self.fence_round = True
                return None
            self._verdict("ladder", plan.reason)
            return None
        cmd = self._confirm(plan.selected)
        if cmd is None or len(cmd.candidates) < 2:
            # probe-vs-host disagreement: the one confirm failed, so the
            # per-candidate ladder (the oracle) decides this round — the
            # shipped command can never differ from the reference's answer
            obs.anomaly("global-confirm-mismatch",
                        registry=self.ctx.registry,
                        selected=len(plan.selected), dropped=plan.dropped)
            self._verdict("ladder", "confirm-mismatch")
            return None
        if plan.displacement:
            # device-side rebinding lever (fused cluster round): hand the
            # displacement plan's survivor targets to the binder so the
            # post-command eviction wave re-binds hint-first instead of
            # cold-scanning the fleet (kube/binder.py seed_wave_hints)
            from karpenter_tpu.kube import binder as _binder

            name_of = {n.provider_id: n.name
                       for n in self.ctx.store.list("nodes")
                       if n.provider_id}
            _binder.seed_wave_hints(
                (name_of[pid], take)
                for pid, _g, take in plan.displacement
                if pid in name_of and take > 0)
        if getattr(plan, "solver", "ladder") == "relax":
            # the LP relaxation rung selected the set (ops/relax.py):
            # relax = rounded at the LP bound, relax-rounded = the
            # window shed candidates below it (both closed enums the
            # GL502 census pins; deploy/README.md "LP relaxation rung")
            self._verdict("joint",
                          "relax" if plan.dropped == 0 else "relax-rounded")
        elif getattr(plan, "relax_fallback", False):
            # the relax rung attempted and declined, the FFD ladder
            # shipped — a command all the same, but the descent is
            # visible (RELAX_STATS carries the cause)
            self._verdict("joint", "relax-fallback")
        elif getattr(plan, "n_claims", 1) > 1:
            # the joint REPLACE program opened multiple fresh claims for
            # one retirement set (KARPENTER_REPLACE_MAX_CLAIMS > 1) — a
            # shape the m->1 delete-row rule would have stranded
            self._verdict("joint", "replace")
        else:
            self._verdict("joint")
        return cmd

    def _confirm(self, selected):
        """The round's ONE real simulation of the joint set — the shared
        :func:`confirm_consolidation` contract, identical to the one the
        MultiNode prefix pays."""
        return confirm_consolidation(self.ctx, selected, "global",
                                     selected=len(selected))


class MultiNodeConsolidation(Method):
    """Largest N where candidates[0..N] collapse into ≤1 replacement
    (disruption/multinodeconsolidation.go:47-163). The prefix search runs
    as ONE batched device probe (ops/consolidate.py) — all N prefixes
    evaluated in a single vmapped pack call — and when the probe declares
    its ladder DEFINITIVE (plan-free, claim accounting provably mirroring
    the simulation's: every modeled host check can only over-estimate)
    the single winning prefix pays the round's only confirming simulation
    and ships. Probe-vs-host disagreement (the confirm at k fails) falls
    back to the reference's sequential binary search below k.
    Non-definitive ladders (topology-compiled bundles, batches too large
    to prove claimability for) keep the upward gallop step around k, so
    the chosen command matches the reference's there at the reference's
    cost; scenarios the probe can't express at all fall back to the full
    sequential search. The whole
    search is bounded by a 1-minute wall clock (multinodeconsolidation.go
    :37): on timeout the best command found so far is returned rather than
    searching unbounded."""

    reason = REASON_UNDERUTILIZED
    needs_validation = True
    is_consolidation = True
    uses_bundle = True
    # "device" | "seeded" | "sequential" (observability + tests) —
    # "seeded" means the answer came from the round's joint dispatch
    # (JointSeed) without paying a second device dispatch
    last_probe: str = ""
    last_host_confirms: int = 0  # host simulations this round (tests + perf)
    _seeded: bool = False

    def compute_command(self, candidates, budgets):
        # reset BEFORE the search: an early return inside _compute (fewer
        # than 2 candidates) must not leave last round's counter behind to
        # fire a spurious anomaly on a quiet round
        self.last_host_confirms = 0
        self.last_probe = ""
        self._seeded = False
        cmd = self._compute(candidates, budgets)
        if self.last_host_confirms > 1:
            # anomaly trigger: the batched confirm ladder targets exactly
            # one host simulation per round (ROADMAP PR 3) — more means
            # probe-vs-host disagreement or a non-definitive ladder, and
            # the round's trace shows which confirm burned the time
            obs.anomaly(
                "multi-host-confirms", registry=self.ctx.registry,
                confirms=self.last_host_confirms, probe=self.last_probe,
            )
        return cmd

    def _compute(self, candidates, budgets):
        pool = _candidate_order(self.ctx, _consolidatable(candidates))
        cands = within_budget(budgets, self.reason, pool)[:MULTI_NODE_CANDIDATE_CAP]
        if len(cands) < 2:
            return None
        self._deadline = self.ctx.clock.now() + MULTI_NODE_TIMEOUT

        probed = self._probe(cands, pool)
        if probed is not None:
            k, definitive = probed
            self.last_probe = "seeded" if self._seeded else "device"
            # the round's probe.confirm verdict (obs/decisions.py): a
            # definitive ladder pays ONE confirming simulation; a
            # non-definitive one keeps the gallop/search around the seed.
            # Seeded answers (the joint dispatch already ranked these
            # prefixes this generation — no second dispatch) carry the
            # joint-seeded reason so the skipped-probe path is accounted,
            # never silent. The sequential rungs were recorded by
            # _device_probe.
            from karpenter_tpu.obs import decisions

            decisions.record_decision(
                "probe.confirm",
                "definitive" if definitive else "gallop",
                ("joint-seeded" if self._seeded
                 else "ok" if definitive else "non-definitive"),
                registry=self.ctx.registry)
            if k < 2:
                # paranoia confirm of the smallest prefix guards the
                # probe's residual false-negative corner (f32 rounding);
                # if it lands, the probe misjudged the batch and the
                # reference's full search takes over
                cmd = self._confirm(cands[:2])
                if cmd is None:
                    return None  # probe confirmed: nothing consolidates
                return self._binary_search(cands, hi=len(cands), lo=3, best=cmd)
            cmd = self._confirm(cands[:k])
            if cmd is not None and len(cmd.candidates) >= 2:
                if definitive or k >= len(cands):
                    # the ladder already proved every prefix above k
                    # infeasible (definitive misses only over-estimate):
                    # this confirm was the round's ONLY host solve
                    return cmd
                # non-definitive ladder: k is a seed, not an answer — one
                # upward gallop step, then resume the search above it
                up = self._confirm(cands[: k + 1])
                if up is not None:
                    return self._binary_search(
                        cands, hi=len(cands), lo=k + 2, best=up
                    )
                return cmd
            # probe-vs-host disagreement (price filter / validation detail
            # the kernel doesn't model): the reference's search below k
            # decides, so the shipped command never differs from its answer
            return self._binary_search(cands, hi=k - 1)
        self.last_probe = "sequential"
        return self._binary_search(cands, hi=len(cands))

    def _probe(self, cands, pool=None):
        from karpenter_tpu.ops.consolidate import batched_feasible_prefix

        seeded = _seed_answer(self.ctx, cands, "prefix")
        if seeded is not None:
            self._seeded = True
            return seeded
        return _device_probe(self.ctx, batched_feasible_prefix, "multi",
                             cands, pool)

    def _confirm(self, prefix):
        """One real simulation of a candidate prefix (the shared
        :func:`confirm_consolidation` contract) with this method's
        host-confirm streak accounting on top."""
        self.last_host_confirms += 1
        return confirm_consolidation(self.ctx, prefix, "multi",
                                     prefix=len(prefix))

    def _timed_out(self) -> bool:
        return _search_timed_out(self.ctx, self._deadline, "multi")

    def _binary_search(self, cands, hi, lo=1, best=None):
        # binary search on prefix length (multinodeconsolidation.go:111-163),
        # returning the best-so-far command when the 1-min budget expires
        # (:124-135)
        while lo <= hi:
            if self._timed_out():
                break
            mid = (lo + hi) // 2
            cmd = self._confirm(cands[:mid])
            if cmd is not None:
                best = cmd
                lo = mid + 1
            else:
                hi = mid - 1
        if best is not None and len(best.candidates) < 2:
            return None  # single-node results belong to SingleNodeConsolidation
        return best


class SingleNodeConsolidation(Method):
    """One-candidate-at-a-time consolidation, abandoned after a 3-minute
    wall clock (disruption/singlenodeconsolidation.go:46-120).

    The reference's linear scan — a full scheduling simulation per
    candidate — runs here as ONE batched device probe
    (ops/consolidate.py batched_single_feasible): every candidate's
    counterfactual is ranked in a single vmapped pack dispatch over the
    round's shared snapshot, and only probe HITS get the real confirming
    simulation (price filter, validation). The probe is a seed, not the
    decision: a confirming hit back-checks every cheaper probe miss before
    shipping (so a false negative can never disrupt a costlier node than
    the reference's lowest-cost-first scan would), and whenever NO hit
    confirms, one paranoia confirmation runs on the cheapest miss (the
    mirror of MultiNode's k<2 confirm) — if it lands, the probe misjudged
    the batch and the method degenerates into the reference's sequential
    scan; if it fails, the probe's negative answer stands for this round
    (the next state change re-probes). Inexpressible scenarios skip the
    probe entirely and run the sequential scan."""

    reason = REASON_UNDERUTILIZED
    needs_validation = True
    is_consolidation = True
    uses_bundle = True
    # "device" | "seeded" | "sequential" (observability + tests)
    last_probe: str = ""
    _seeded: bool = False

    def compute_command(self, candidates, budgets):
        self._seeded = False
        pool = _candidate_order(self.ctx, _consolidatable(candidates))
        cands = within_budget(budgets, self.reason, pool)
        if not cands:
            return None
        deadline = self.ctx.clock.now() + SINGLE_NODE_TIMEOUT
        probed = self._probe(cands, pool)
        if probed is None:
            self.last_probe = "sequential"
            res = self._scan(cands, deadline)
            return None if res is _TIMED_OUT else res
        feas, definitive = probed
        self.last_probe = "seeded" if self._seeded else "device"
        # one probe.confirm verdict per ladder descent, mirroring
        # MultiNode's (sequential rungs recorded by _device_probe;
        # joint-seeded answers paid no dispatch of their own)
        from karpenter_tpu.obs import decisions

        decisions.record_decision(
            "probe.confirm",
            "definitive" if definitive else "gallop",
            ("joint-seeded" if self._seeded
             else "ok" if definitive else "non-definitive"),
            registry=self.ctx.registry)
        # confirm hits in disruption-cost order; probe misses are only
        # SKIPPED, never discarded: when a hit confirms, any miss that
        # precedes it is back-checked first so a probe false negative can
        # never make the method ship a costlier node than the reference's
        # lowest-cost-first scan would (the result is exactly the first
        # candidate — in order — that the exact simulation accepts, up to
        # and including the first confirming hit)
        any_hit = False
        skipped: list = []
        for c, ok in zip(cands, feas):
            if not ok:
                skipped.append(c)
                continue
            any_hit = True
            if self._timed_out(deadline):
                return None  # abandon mid-scan (:71-75)
            cmd = self._confirm_one(c)
            if cmd is None:
                continue
            earlier = self._scan(skipped, deadline)
            if earlier is _TIMED_OUT:
                # an exhausted budget mid-back-check means the cheaper
                # misses were NEVER cleared: shipping the later hit would
                # disrupt a costlier node than the reference's lowest-cost-
                # first scan ever could — abandon, like the reference does
                return None
            return earlier if earlier is not None else cmd
        if skipped:
            if not definitive:
                # topology bundle: misses are hints, not answers (the waves
                # counterfactual can tighten the probe) — finish with the
                # reference's scan so no consolidation is silently skipped
                res = self._scan(skipped, deadline)
                return None if res is _TIMED_OUT else res
            # no hit confirmed: one paranoia simulation of the cheapest
            # skipped miss guards the definitive probe's residual
            # false-negative corner (f32 fit rounding); if it lands the
            # probe misjudged the batch
            if self._timed_out(deadline):
                return None
            cmd = self._confirm_one(skipped[0])
            if cmd is not None:
                return cmd
            if any_hit and skipped[1:]:
                # hits existed but ALL confirms failed — the probe is
                # demonstrably misaligned with the exact checks this round,
                # so finish with the reference's scan rather than skipping
                res = self._scan(skipped[1:], deadline)
                return None if res is _TIMED_OUT else res
        return None

    def _scan(self, cands, deadline):
        """The reference's linear scan (singlenodeconsolidation.go:64-89).
        Returns the first confirmed command, None when every candidate was
        exhausted, or _TIMED_OUT when the wall clock expired mid-scan — the
        back-check caller must distinguish 'cleared' from 'never checked'."""
        for c in cands:
            if self._timed_out(deadline):
                return _TIMED_OUT  # abandon mid-scan (:71-75)
            cmd = self._confirm_one(c)
            if cmd is not None:
                return cmd
        return None

    def _confirm_one(self, c):
        """One real simulation of a single candidate, with host-confirm
        accounting (the perf harness's `host_confirm_count`)."""
        from karpenter_tpu.operator import metrics as m

        self.ctx.registry.counter(
            m.DISRUPTION_HOST_CONFIRMS,
            "confirming host simulations run by consolidation methods",
        ).inc(method="single")
        with obs.span("confirm.simulate", method="single"), \
                self.ctx.registry.measure(m.DISRUPTION_CONFIRM_DURATION,
                                          method="single"):
            return compute_consolidation(self.ctx, [c])

    def _timed_out(self, deadline) -> bool:
        return _search_timed_out(self.ctx, deadline, "single")

    def _probe(self, cands, pool=None):
        from karpenter_tpu.ops.consolidate import batched_single_feasible

        seeded = _seed_answer(self.ctx, cands, "single")
        if seeded is not None:
            self._seeded = True
            return seeded
        return _device_probe(self.ctx, batched_single_feasible, "single",
                             cands, pool)
