"""Candidate discovery, budgets, and the counterfactual simulation.

Mirror of the reference's pkg/controllers/disruption/helpers.go:
`get_candidates` (:146-193) filters cluster state to disruptable nodes;
`build_disruption_budgets` (:199-254) computes per-nodepool per-reason
allowances net of nodes already disrupting; `simulate_scheduling` (:51-115)
answers "if these nodes were gone, where would their pods go?" by running
the full solver against the remaining state.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.nodepool import ALL_REASONS
from karpenter_tpu.controllers.disruption.types import Candidate
from karpenter_tpu.utils import pod as pod_util
from karpenter_tpu.utils.pdb import PdbLimits


def get_candidates(cluster, store, cloud, clock, queue=None,
                   catalog_cache=None) -> list:
    """Disruptable nodes as Candidates (helpers.go:146).

    ``catalog_cache`` optionally carries a nodepool-name -> {type name:
    InstanceType} memo owned by the disruption controller: candidate
    discovery runs at least twice per executed command (compute +
    validate) and every poll round otherwise, and re-listing the cloud
    provider each time is pure waste for providers where GetInstanceTypes
    is a real API call. The controller clears it on nodepool events; the
    catalog objects themselves are shared by identity with the solver's
    type cache, so in-place offering flips stay visible."""
    pdb_limits = PdbLimits(store)
    pools = {np.name: np for np in store.list("nodepools")}
    catalogs: dict = catalog_cache if catalog_cache is not None else {}
    out = []
    for sn in cluster.nodes():
        if sn.deleting() or sn.marked_for_deletion:
            continue
        if queue is not None and queue.has_candidate(sn.provider_id):
            continue
        if sn.nominated(clock.now()):
            continue
        if sn.validate_disruptable(pdb_limits) is not None:
            continue
        np = pools.get(sn.nodepool_name)
        if np is None:
            continue
        if np.name not in catalogs:
            catalogs[np.name] = {it.name: it for it in cloud.get_instance_types(np)}
        it = catalogs[np.name].get(sn.labels().get(wk.INSTANCE_TYPE_LABEL, ""))
        out.append(Candidate(sn, np, it, clock))
    return out


def build_disruption_budgets(cluster, store, clock) -> dict:
    """nodepool name -> reason -> allowed disruptions (helpers.go:199)."""
    totals: dict = {}
    disrupting: dict = {}
    # read-only aggregation: the live StateNodes suffice — no snapshot copy
    for sn in cluster.state_nodes():
        pool = sn.nodepool_name
        if not pool:
            continue
        totals[pool] = totals.get(pool, 0) + 1
        if sn.marked_for_deletion or sn.deleting() or not sn.initialized():
            disrupting[pool] = disrupting.get(pool, 0) + 1
    budgets: dict = {}
    now = clock.now()
    for np in store.list("nodepools"):
        total = totals.get(np.name, 0)
        already = disrupting.get(np.name, 0)
        budgets[np.name] = {
            reason: max(np.allowed_disruptions(reason, total, now) - already, 0)
            for reason in ALL_REASONS
        }
    return budgets


def within_budget(budgets: dict, reason: str, candidates) -> list:
    """Longest prefix of candidates whose per-pool budgets all hold
    (the reference trims candidate lists per nodepool budget)."""
    spent: dict = {}
    out = []
    for c in candidates:
        pool = c.node_pool.name
        allowed = budgets.get(pool, {}).get(reason, 0)
        if spent.get(pool, 0) + 1 > allowed:
            continue
        spent[pool] = spent.get(pool, 0) + 1
        out.append(c)
    return out


class SimulationResults:
    def __init__(self, results, candidate_pods):
        self.results = results
        self.candidate_pods = candidate_pods

    @property
    def new_claims(self):
        return self.results.new_claims

    def all_pods_scheduled(self) -> bool:
        """Every reschedulable pod from the candidates found a home
        (helpers.go:104: pods failing or landing nowhere block the
        command)."""
        placed = set()
        for claim in self.results.new_claims:
            placed.update(p.uid for p in claim.pods)
        for node in self.results.existing_nodes:
            placed.update(p.uid for p in getattr(node, "scheduled_pods", []) or [])
        return all(p.uid in placed for p in self.candidate_pods)


def simulate_scheduling(provisioner, cluster, store, candidates, inputs=None,
                        bundle=None) -> SimulationResults:
    """Counterfactual solve: cluster minus candidates (helpers.go:51).

    `inputs` optionally carries pre-assembled solver inputs (templates,
    catalog, overhead, limits, domains) from the round's snapshot cache
    (ops/consolidate.py) — valid only within one cluster-state generation,
    which the cache's `inputs_for` enforces before handing them out.

    `bundle` optionally carries the round's DisruptionSnapshot: when still
    generation-current it supplies the existing-node view directly — the
    candidate-free node set as cheap forks of tensorized prototypes
    (`sim_enodes`) and the solver's existing-node tensors as row slices of
    the shared snapshot (`derive_esnap`) — so a confirming simulation
    skips the O(E) snapshot+constructor sweep and the O(E×G) re-tensorize
    that otherwise dominate every confirm. The result is the same solve
    over the same state; the fast path only ever changes how the inputs
    are materialized, and declines to None-mapped candidates."""
    excluded = {c.provider_id for c in candidates}
    candidate_pods = [p for c in candidates for p in c.reschedulable_pods]
    pending = [p for p in store.list("pods") if pod_util.is_provisionable(p)]
    if bundle is not None and bundle.generation == cluster.consolidation_state():
        protos = bundle.sim_enodes(excluded)
        if protos is not None:
            seen = {p.uid for p in pending}
            seen.update(p.uid for p in candidate_pods)
            deleting = bundle.sim_deleting_pods(seen)
            results = provisioner.schedule(
                pods=pending + candidate_pods + deleting, state_nodes=[],
                inputs=inputs, enodes_base=protos, existing_base=bundle,
            )
            return SimulationResults(results, candidate_pods)
    state_nodes = [sn for sn in cluster.nodes() if sn.provider_id not in excluded]
    deleting = provisioner.deleting_node_pods(state_nodes, pending + candidate_pods)
    results = provisioner.schedule(
        pods=pending + candidate_pods + deleting, state_nodes=state_nodes,
        inputs=inputs,
    )
    return SimulationResults(results, candidate_pods)
