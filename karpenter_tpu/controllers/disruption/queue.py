"""Orchestration queue: command execution lifecycle.

Mirror of the reference's pkg/controllers/disruption/orchestration/queue.go:
after a command is admitted — candidates tainted, replacements launched —
the queue waits for every replacement NodeClaim to initialize, then deletes
the candidate claims (:165-294). Commands that cannot complete within
`MAX_RETRY_DURATION` roll back: candidates are untainted and unmarked so
provisioning/disruption see them as healthy again (:56, :226-294);
replacement claims are left for the emptiness path to reap.
"""

from __future__ import annotations

import time

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import Taint

MAX_RETRY_DURATION = 10 * 60.0  # queue.go:56

# process-wide command-orchestration accounting, delta'd by
# `python -m perf global` (the orchestrate_ms slice of the post-command
# wave's breakdown — replacement waits, candidate-claim deletion,
# rollbacks; the drain and rebind halves live in
# controllers/node/termination.py and kube/binder.py STATS)
STATS = {
    "orchestrate_ms": 0.0,
    "polls": 0,
}

DISRUPTION_TAINT = Taint(
    key=wk.DISRUPTION_TAINT_KEY, value=wk.DISRUPTION_TAINT_VALUE, effect="NoSchedule"
)


def add_disruption_taint(store, node) -> bool:
    if any(t.key == wk.DISRUPTION_TAINT_KEY for t in node.taints):
        return False
    node.taints.append(DISRUPTION_TAINT)
    store.update("nodes", node)
    return True


def remove_disruption_taint(store, node) -> bool:
    kept = [t for t in node.taints if t.key != wk.DISRUPTION_TAINT_KEY]
    if len(kept) == len(node.taints):
        return False
    node.taints = kept
    store.update("nodes", node)
    return True


class OrchestrationQueue:
    def __init__(self, store, cluster, clock, recorder=None):
        self.store = store
        self.cluster = cluster
        self.clock = clock
        self.recorder = recorder
        self.commands: list = []

    def has_candidate(self, provider_id: str) -> bool:
        return any(
            c.provider_id == provider_id for cmd in self.commands for c in cmd.candidates
        )

    def add(self, command):
        command.created_at = self.clock.now()
        self.commands.append(command)

    def poll(self) -> bool:
        if not self.commands:
            return False
        t0 = time.perf_counter()
        progressed = False
        remaining = []
        for cmd in self.commands:
            done, moved = self._reconcile(cmd)
            progressed |= moved
            if not done:
                remaining.append(cmd)
        self.commands = remaining
        STATS["orchestrate_ms"] += (time.perf_counter() - t0) * 1000.0
        STATS["polls"] += 1
        return progressed

    def _reconcile(self, cmd) -> tuple:
        """(done, progressed) — wait replacements Initialized, then delete
        candidates (queue.go waitOrTerminate:226)."""
        if self.clock.now() - cmd.created_at > MAX_RETRY_DURATION:
            self._rollback(cmd)
            return True, True
        for name in cmd.replacement_names:
            claim = self.store.try_get("nodeclaims", name)
            if claim is None:
                # a replacement died (e.g. insufficient capacity, liveness):
                # unrecoverable — roll back (queue.go:268)
                self._rollback(cmd)
                return True, True
            if not claim.initialized:
                return False, False  # keep waiting
        # all replacements ready: delete the candidates
        for c in cmd.candidates:
            claim = c.state_node.node_claim
            if claim is None:
                continue
            existing = self.store.try_get("nodeclaims", claim.name)
            if existing is not None and existing.metadata.deletion_timestamp is None:
                self.store.delete("nodeclaims", existing)
        if self.recorder is not None:
            self.recorder.publish(
                "DisruptionTerminating",
                f"{cmd.reason}: deleting {[c.name for c in cmd.candidates]}",
            )
        return True, True

    def _rollback(self, cmd):
        """Untaint + unmark so the cluster returns to steady state
        (queue.go:272-294)."""
        cmd.last_error = "command timed out or replacement failed"
        for c in cmd.candidates:
            node = self.store.try_get("nodes", c.name)
            if node is not None:
                remove_disruption_taint(self.store, node)
        self.cluster.unmark_for_deletion(*[c.provider_id for c in cmd.candidates])
        if self.recorder is not None:
            self.recorder.publish(
                "DisruptionFailed", f"rolled back command for {[c.name for c in cmd.candidates]}"
            )
