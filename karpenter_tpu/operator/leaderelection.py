"""Lease-based leader election — the controller-runtime analog.

The reference operator runs with leader election on a coordination.k8s.io
Lease (pkg/operator/operator.go NewOperator: LeaderElection enabled,
LeaderElectionID "karpenter-leader-election"): only the lease holder runs
controllers; standbys poll and take over when the lease expires. The
hermetic build elects through the store's "leases" kind with the same
acquire/renew/release protocol so multi-instance deployments (or tests)
get single-writer semantics.
"""

from __future__ import annotations

from karpenter_tpu.api.objects import ObjectMeta

LEASE_NAME = "karpenter-leader-election"
LEASE_DURATION = 15.0  # controller-runtime defaults
RENEW_DEADLINE = 10.0
RETRY_PERIOD = 2.0


class _Lease:
    def __init__(self, name, holder, acquired, renewed, duration):
        self.metadata = ObjectMeta(name=name, namespace="kube-system")
        self.holder = holder
        self.acquired = acquired
        self.renewed = renewed
        self.duration = duration


class LeaderElector:
    def __init__(self, store, identity: str, clock=None,
                 lease_duration: float = LEASE_DURATION):
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.identity = identity
        self.clock = clock or Clock()
        self.lease_duration = lease_duration

    def _lease(self):
        return self.store.try_get("leases", LEASE_NAME, namespace="kube-system")

    def is_leader(self) -> bool:
        lease = self._lease()
        return (
            lease is not None
            and lease.holder == self.identity
            and self.clock.now() - lease.renewed < self.lease_duration
        )

    # whether the most recent successful try_acquire TOOK the lease
    # (first creation, or expiry takeover from another holder) rather
    # than renewing this identity's own: only a real takeover requires
    # the informer-cache resync — the store's watch queue is
    # single-consumer and only the leader drains it, so a leader
    # re-acquiring its OWN stale lease (a fake-clock jump, a long GC
    # pause with no contender) has missed nothing, and resyncing there
    # would needlessly journal an opaque consolidation bump every time
    # the clock outruns the lease duration
    last_acquire_takeover: bool = False

    def try_acquire(self) -> bool:
        """Acquire or renew; True iff this identity holds the lease after
        the call (leaderelection.go tryAcquireOrRenew)."""
        now = self.clock.now()
        lease = self._lease()
        if lease is None:
            lease = _Lease(LEASE_NAME, self.identity, now, now, self.lease_duration)
            try:
                self.store.create("leases", lease)
            except Exception:
                return self.is_leader()  # lost the race
            self.last_acquire_takeover = True
            return True
        expired = now - lease.renewed >= lease.duration
        if lease.holder == self.identity:
            # renew at most once per RETRY_PERIOD: an update per reconcile
            # round would flood the watch stream (and read as progress to
            # idle detection). Renewing our OWN lease — even one the clock
            # let expire — is not a takeover: the holder never changed, so
            # no other instance can have drained the event queue meanwhile
            self.last_acquire_takeover = False
            if now - lease.renewed >= RETRY_PERIOD:
                lease.renewed = now
                self.store.update("leases", lease)
            return True
        if expired:
            lease.holder = self.identity
            lease.acquired = now
            lease.renewed = now
            self.store.update("leases", lease)
            self.last_acquire_takeover = True
            return True
        return False

    def release(self):
        """Voluntary hand-off on shutdown (releaseOnCancel)."""
        lease = self._lease()
        if lease is not None and lease.holder == self.identity:
            # expire relative to NOW — an absolute 0.0 only reads as
            # expired once the clock has advanced past the duration
            lease.renewed = self.clock.now() - lease.duration
            self.store.update("leases", lease)
