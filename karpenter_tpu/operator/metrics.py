"""In-process metrics: Prometheus-shaped counters/gauges/histograms.

Mirror of the reference's pkg/metrics (metrics.go:30-148, constants.go:65):
the same namespaced metric families (karpenter_*), a `measure()` timer that
plays the role of the reference's `metrics.Measure` closure helper, and a
text exposition dump compatible with the Prometheus format so an operator
can scrape or snapshot it. No client library dependency — the registry is
a couple of dicts guarded by a lock, cheap enough to sit on the solve path.

Readers (``value``/``count``/``sum``) and the per-family ``expose`` hold
the same registry lock the writers do: an unlocked read racing ``inc``
can observe a half-applied sweep (clear-then-set gauges, a histogram
whose bucket counts moved but whose ``_sum`` hasn't) — exposition must be
a consistent snapshot, not a torn one.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

NAMESPACE = "karpenter"

DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0,
)


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    def __init__(self, name: str, help: str, registry: "Registry"):
        self.name = name
        self.help = help
        self._lock = registry._lock

    def _expose_header(self, kind: str) -> list:
        return [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {kind}"]


class Counter(_Metric):
    def __init__(self, name, help, registry):
        super().__init__(name, help, registry)
        self._values: dict = {}

    def inc(self, amount: float = 1.0, **labels):
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labels_key(labels), 0.0)

    def total(self) -> float:
        """Sum across every label combination — aggregate reads (e.g. "did
        ANY fallback happen", regardless of code/reason labels)."""
        with self._lock:
            return sum(self._values.values())

    def expose(self) -> list:
        out = self._expose_header("counter")
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name, help, registry):
        super().__init__(name, help, registry)
        self._values: dict = {}

    def set(self, value: float, **labels):
        with self._lock:
            self._values[_labels_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labels_key(labels), 0.0)

    def clear(self):
        """Exporters rebuild the full gauge family each sweep (the
        controllers/metrics/* pattern of delete-then-set)."""
        with self._lock:
            self._values.clear()

    def remove(self, **labels):
        """Delete ONE label combination — for exporters that reconcile a
        partial view (e.g. one pool's catalog) and must retire exactly
        the series they own without clearing the whole family."""
        with self._lock:
            self._values.pop(_labels_key(labels), None)

    def expose(self) -> list:
        out = self._expose_header("gauge")
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Histogram(_Metric):
    def __init__(self, name, help, registry, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, registry)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict = {}  # labels -> [per-bucket cumulative-ready counts]
        self._sum: dict = {}
        self._total: dict = {}

    def observe(self, value: float, **labels):
        key = _labels_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sum[key] = self._sum.get(key, 0.0) + value
            self._total[key] = self._total.get(key, 0) + 1

    def remove(self, **labels):
        """Delete ONE label combination — Gauge.remove parity, so a
        bounded-cardinality owner (the fleet ledger's per-tenant billing,
        obs/timeline.py) can retire exactly the series of a tenant whose
        rolling sub-window LRU-dropped."""
        key = _labels_key(labels)
        with self._lock:
            self._counts.pop(key, None)
            self._sum.pop(key, None)
            self._total.pop(key, None)

    def count(self, **labels) -> int:
        with self._lock:
            return self._total.get(_labels_key(labels), 0)

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sum.get(_labels_key(labels), 0.0)

    def expose(self) -> list:
        out = self._expose_header("histogram")
        with self._lock:
            snapshot = [
                (key, list(self._counts[key]), self._sum[key], self._total[key])
                for key in sorted(self._total)
            ]
        for key, counts, total_sum, total in snapshot:
            for i, b in enumerate(self.buckets):
                bkey = key + (("le", str(b)),)
                out.append(f"{self.name}_bucket{_fmt_labels(bkey)} {counts[i]}")
            out.append(f"{self.name}_bucket{_fmt_labels(key + (('le', '+Inf'),))} {total}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {total_sum}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {total}")
        return out


class Registry:
    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict = {}

    def _get_or_make(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(Histogram, name, help, buckets=buckets)

    def expose(self) -> str:
        """Prometheus text exposition of every registered family."""
        lines = []
        with self._lock:
            for name in sorted(self._metrics):
                lines.extend(self._metrics[name].expose())
        return "\n".join(lines) + "\n"

    @contextmanager
    def measure(self, histogram_name: str, **labels):
        """Timer context: the reference's metrics.Measure closure
        (pkg/metrics/constants.go:65)."""
        hist = self.histogram(histogram_name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            hist.observe(time.perf_counter() - t0, **labels)


# the default in-process registry, the controller-runtime-registry analog
REGISTRY = Registry()

# well-known family names (pkg/metrics/constants.go + per-package metrics.go)
SCHEDULING_DURATION = f"{NAMESPACE}_provisioner_scheduling_duration_seconds"
SCHEDULING_QUEUE_DEPTH = f"{NAMESPACE}_provisioner_scheduling_queue_depth"
IGNORED_PODS = f"{NAMESPACE}_provisioner_ignored_pod_count"
NODECLAIMS_CREATED = f"{NAMESPACE}_nodeclaims_created_total"
NODECLAIMS_TERMINATED = f"{NAMESPACE}_nodeclaims_terminated_total"
NODECLAIMS_LAUNCHED = f"{NAMESPACE}_nodeclaims_launched_total"
NODECLAIMS_REGISTERED = f"{NAMESPACE}_nodeclaims_registered_total"
NODECLAIMS_INITIALIZED = f"{NAMESPACE}_nodeclaims_initialized_total"
DISRUPTION_EVAL_DURATION = f"{NAMESPACE}_disruption_evaluation_duration_seconds"
DISRUPTION_ACTIONS = f"{NAMESPACE}_disruption_actions_performed_total"
DISRUPTION_ELIGIBLE_NODES = f"{NAMESPACE}_disruption_eligible_nodes"
DISRUPTION_PODS = f"{NAMESPACE}_disruption_pods_disrupted_total"
DISRUPTION_BUDGETS = f"{NAMESPACE}_disruption_allowed_disruptions"
CONSOLIDATION_TIMEOUTS = f"{NAMESPACE}_disruption_consolidation_timeouts_total"
DISRUPTION_PROBE_FAILURES = f"{NAMESPACE}_disruption_probe_failures_total"
DISRUPTION_SNAPSHOT_CACHE_HITS = (
    f"{NAMESPACE}_disruption_snapshot_cache_hits_total"
)
DISRUPTION_SNAPSHOT_CACHE_MISSES = (
    f"{NAMESPACE}_disruption_snapshot_cache_misses_total"
)
DISRUPTION_PROBE_BATCH_SIZE = f"{NAMESPACE}_disruption_probe_batch_size"
# confirming host simulations per consolidation method ("multi"/"single"):
# the batched confirm ladder targets ≤1 per MultiNode round — a climbing
# count means probe-vs-host disagreement (sequential fallbacks)
DISRUPTION_HOST_CONFIRMS = f"{NAMESPACE}_disruption_host_confirms_total"
DISRUPTION_CONFIRM_DURATION = (
    f"{NAMESPACE}_disruption_confirm_duration_seconds"
)
# negative node availabilities clamped during tensorization — mirrored from
# ops/tensorize.py (capacity-accounting bugs must surface, not vanish)
TENSORIZE_NEGATIVE_AVAIL = f"{NAMESPACE}_tensorize_negative_avail_total"
# pods each live solve routed to the host engine instead of the device
# path, by reason label (waves compiler inexpressibles, spec ineligibility,
# small-batch cutoff) — a grid regression shows up here as a reason spike
PROVISIONING_HOST_ROUTED = f"{NAMESPACE}_provisioning_host_routed_pods_total"
# spot resilience (deploy/README.md "Spot resilience"): interruption
# notices pulled from the cloud provider (outcome=marked|unknown-node),
# nodes drained proactively ahead of their notice deadline, notices whose
# deadline forced the degraded immediate-drain path, and the per-offering
# interruption-risk signal (labels instance_type/zone/capacity_type,
# known nonzero risks only — exported by cloudprovider/metrics.py)
INTERRUPTION_NOTICES = f"{NAMESPACE}_interruption_notices_total"
INTERRUPTION_PROACTIVE_DRAINS = (
    f"{NAMESPACE}_interruption_proactive_drains_total"
)
INTERRUPTION_DEADLINE_DEGRADATIONS = (
    f"{NAMESPACE}_interruption_deadline_degradations_total"
)
OFFERING_RISK = f"{NAMESPACE}_offering_risk"
# admission plane (karpenter_tpu/admission): victim pods evicted by a
# confirmed preemption, and preemption ladder outcomes by outcome label
# (the per-rung mix also rides karpenter_decision_total{site="admission.*"})
ADMISSION_EVICTIONS = f"{NAMESPACE}_admission_preemption_evictions_total"
ADMISSION_PREEMPTIONS = f"{NAMESPACE}_admission_preemptions_total"
# counterfactual-rows-per-dispatch buckets (powers of two up to the probe's
# chunk cap) — durations make no sense for a size histogram
PROBE_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
DISRUPTION_ABNORMAL_RUNS = f"{NAMESPACE}_disruption_abnormal_runs_total"
NODECLAIMS_DISRUPTED = f"{NAMESPACE}_nodeclaims_disrupted_total"
CLUSTER_STATE_SYNCED = f"{NAMESPACE}_cluster_state_synced"
CLOUDPROVIDER_DURATION = f"{NAMESPACE}_cloudprovider_duration_seconds"
CLOUDPROVIDER_ERRORS = f"{NAMESPACE}_cloudprovider_errors_total"
SOLVER_REMOTE_FALLBACKS = f"{NAMESPACE}_solver_remote_fallbacks_total"
PODS_STATE = f"{NAMESPACE}_pods_state"
PODS_STARTUP_DURATION = f"{NAMESPACE}_pods_startup_duration_seconds"
NODES_CREATED = f"{NAMESPACE}_nodes_created_total"
NODES_TERMINATED = f"{NAMESPACE}_nodes_terminated_total"
NODE_TERMINATION_DURATION = f"{NAMESPACE}_nodes_termination_duration_seconds"
NODECLAIM_TERMINATION_DURATION = (
    f"{NAMESPACE}_nodeclaims_termination_duration_seconds"
)
# device-plane telemetry (karpenter_tpu/obs/devplane.py): the compile
# ledger (cold-compile events/wall time + resident executable families),
# pow-2 padding-waste fractions per dispatch site, and the solver-service
# SLO surfaces (request histogram, rolling quantile gauges, error-budget
# burn) — see deploy/README.md "Device-plane & SLO telemetry"
COMPILE_EVENTS = f"{NAMESPACE}_compile_events_total"
COMPILE_SECONDS = f"{NAMESPACE}_compile_seconds"
COMPILE_FAMILIES = f"{NAMESPACE}_compile_families_resident"
PAD_WASTE_RATIO = f"{NAMESPACE}_pad_waste_ratio"
# waste is a fraction in [0,1]; duration buckets make no sense for it
PAD_WASTE_BUCKETS = (0.0, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.625, 0.75,
                     0.875, 1.0)
# partitioned mesh solve (parallel/mesh.py): pipelined-tensorize overlap
# seconds, straddling pods re-packed by the bounded repair pass, and
# fallbacks out of the partitioned rung by reason
SHARD_OVERLAP_SECONDS = f"{NAMESPACE}_shard_tensorize_overlap_seconds_total"
SHARD_REPAIR_PODS = f"{NAMESPACE}_shard_repair_pods_total"
SHARD_FALLBACKS = f"{NAMESPACE}_shard_fallbacks_total"
SOLVER_REQUEST_SECONDS = f"{NAMESPACE}_solver_request_seconds"
SOLVER_REQUEST_QUANTILE = f"{NAMESPACE}_solver_request_quantile_seconds"
SLO_BUDGET_BURN = f"{NAMESPACE}_slo_error_budget_burn_total"
# multi-tenant solver fleet service (service/session.py + solver_service.py):
# per-tenant request counters on the SLO plane, session-cache efficacy with
# an LRU byte budget, streaming-delta resync accounting, the coalescer's
# batched-dispatch shape, admission rejections, transport retries, wire
# payload sizes, and the cross-tenant-bleed assertion hook — see
# deploy/README.md "Multi-tenant solver service"
SOLVER_TENANT_REQUESTS = f"{NAMESPACE}_solver_tenant_requests_total"
SOLVER_SESSIONS = f"{NAMESPACE}_solver_sessions_active"
SOLVER_SESSION_CACHE_HITS = f"{NAMESPACE}_solver_session_cache_hits_total"
SOLVER_SESSION_CACHE_STORES = f"{NAMESPACE}_solver_session_cache_stores_total"
SOLVER_SESSION_CACHE_EVICTIONS = (
    f"{NAMESPACE}_solver_session_cache_evictions_total"
)
SOLVER_SESSION_CACHE_BYTES = f"{NAMESPACE}_solver_session_cache_bytes"
SOLVER_SESSION_RESYNCS = f"{NAMESPACE}_solver_session_resyncs_total"
SOLVER_COALESCED = f"{NAMESPACE}_solver_coalesced_requests_total"
SOLVER_COALESCE_BATCH = f"{NAMESPACE}_solver_coalesce_batch_size"
# requests folded per dispatch window — powers of two like the probe's
SOLVER_COALESCE_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
SOLVER_ADMISSION_REJECTS = f"{NAMESPACE}_solver_admission_rejects_total"
SOLVER_REMOTE_RETRIES = f"{NAMESPACE}_solver_remote_retries_total"
SOLVER_REQUEST_BYTES = f"{NAMESPACE}_solver_request_bytes"
# wire payload sizes: bytes, not seconds
SOLVER_REQUEST_BYTES_BUCKETS = (
    1e3, 1e4, 1e5, 1e6, 5e6, 1e7, 5e7, 1e8, 2.56e8,
)
SOLVER_BLEED_CHECKS = f"{NAMESPACE}_solver_bleed_checks_total"
# decision plane (karpenter_tpu/obs/decisions.py): one ladder verdict per
# site invocation (labels site/rung/reason, reasons drawn from the closed
# per-site enums so cardinality is bounded), the per-solve node-count
# overhead over the solver's pods-cap floor, and per-multichip-solve shard
# balance (max/mean hybrid shard weight, parallel/mesh.py plan_shards) —
# see deploy/README.md "Decision plane"
DECISION_TOTAL = f"{NAMESPACE}_decision_total"
SOLVE_OVERHEAD_RATIO = f"{NAMESPACE}_solve_overhead_ratio"
SHARD_BALANCE_RATIO = f"{NAMESPACE}_shard_balance_ratio"
# span-derived families fed by the reconcile flight recorder
# (karpenter_tpu/obs): per-span self time, round durations, anomaly
# trigger counts, and trace files written
TRACE_SPAN_SECONDS = f"{NAMESPACE}_trace_span_self_seconds"
TRACE_ROUND_SECONDS = f"{NAMESPACE}_trace_round_duration_seconds"
TRACE_ANOMALIES = f"{NAMESPACE}_trace_anomalies_total"
TRACE_DUMPS = f"{NAMESPACE}_trace_dumps_total"
# replay capsules (karpenter_tpu/obs/capsule.py): capsule files written
# next to the Chrome dumps (labels seam + why = anomaly|forced), and
# captures skipped by the KARPENTER_CAPSULE_BYTES size budget
CAPSULE_WRITES = f"{NAMESPACE}_capsule_writes_total"
CAPSULE_SKIPPED = f"{NAMESPACE}_capsule_skipped_total"
# session-GC sweeps on the solver fleet service (service/session.py
# SessionRegistry.sweep): each sweep reaps expired sessions and releases
# their bundle bytes from the LRU budget without waiting for a client
# access to trip the reap-on-access path
SOLVER_SESSION_SWEEPS = f"{NAMESPACE}_solver_session_sweeps_total"
# fleet ledger (karpenter_tpu/obs/timeline.py): effective-price dollars
# integrated over node lifetimes, predicted vs realized savings rates of
# reconciled disruption commands, per-tenant device-time billing (the
# histogram's tenant series retire via Histogram.remove when the tenant's
# SLO sub-window LRU-drops), and committed lifecycle-timeline events by
# kind — see deploy/README.md "Fleet ledger"
FLEET_COST_REALIZED = f"{NAMESPACE}_fleet_cost_realized_total"
FLEET_SAVINGS_PREDICTED = f"{NAMESPACE}_fleet_savings_predicted_total"
FLEET_SAVINGS_REALIZED = f"{NAMESPACE}_fleet_savings_realized_total"
TENANT_DEVICE_SECONDS = f"{NAMESPACE}_tenant_device_seconds_total"
TENANT_DISPATCH_SECONDS = f"{NAMESPACE}_tenant_dispatch_seconds"
TIMELINE_EVENTS = f"{NAMESPACE}_timeline_events_total"
NODES_ALLOCATABLE = f"{NAMESPACE}_nodes_allocatable"
NODES_TOTAL = f"{NAMESPACE}_nodes_count"
NODEPOOL_USAGE = f"{NAMESPACE}_nodepool_usage"
NODEPOOL_LIMIT = f"{NAMESPACE}_nodepool_limit"
