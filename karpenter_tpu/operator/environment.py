"""Operator runtime: wires the store, provider, and controller ring.

This is both the production wiring (the analog of the reference's
kwok/main.go:33-48 + operator.NewOperator, operator.go:111) and the test
harness (the envtest analog, pkg/test/environment.go): controllers are
driven synchronously by draining store events until the system quiesces,
exactly how the reference suites drive reconcilers with
ExpectSingletonReconciled (expectations.go:174).
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
from karpenter_tpu.controllers.nodeclaim.lifecycle import NodeClaimLifecycleController
from karpenter_tpu.controllers.provisioning.provisioner import Provisioner
from karpenter_tpu.kube import Binder, KubeStore
from karpenter_tpu.state import Cluster
from karpenter_tpu.utils.clock import FakeClock


class Environment:
    def __init__(
        self,
        instance_types=None,
        clock=None,
        cloud=None,
        solver=None,
        sync: bool = True,
        enable_disruption: bool = False,
        disruption_options: dict | None = None,
        validation_ttl: float | None = None,
        provider_metrics: bool = True,
        options=None,
        store=None,  # share an apiserver across instances (HA/standby)
        log=None,  # structured Logger; tests default to NOP (quiet)
    ):
        from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
        from karpenter_tpu.controllers.provisioning.batcher import Batcher
        from karpenter_tpu.operator.events import Recorder
        from karpenter_tpu.operator.logging import NOP
        from karpenter_tpu.operator.metrics import Registry
        from karpenter_tpu.operator.options import Options

        self.options = options or Options.from_env()
        self.log = log if log is not None else NOP
        self.clock = clock or FakeClock()
        self.store = store or KubeStore(self.clock)
        self.recorder = Recorder(clock=self.clock)
        # per-environment registry: two Environments in one process (the
        # pytest norm) must not clobber each other's gauge sweeps
        self.registry = Registry()
        self.cloud = cloud or KwokCloudProvider(self.store, instance_types)
        if provider_metrics and not isinstance(self.cloud, MetricsCloudProvider):
            self.cloud = MetricsCloudProvider(self.cloud, registry=self.registry)
        self.binder = Binder(self.store, clock=self.clock, registry=self.registry)
        self.cluster = Cluster(self.store, clock=self.clock)
        # session-mode remote solvers ship the cluster's delta journal as
        # the wire protocol's provenance window (service/solver_service.py
        # RemoteSolver.bind_cluster); in-process solvers have no such hook
        if solver is not None and hasattr(solver, "bind_cluster"):
            solver.bind_cluster(self.cluster)
        # leader election gates every reconcile round (operator.go
        # LeaderElection): a single-instance environment always holds the
        # lease; a standby Environment sharing the store stays passive
        import uuid

        from karpenter_tpu.operator.leaderelection import LeaderElector

        # identity must be unique per INSTANCE lifetime: id(self) is reused
        # after GC, which would let a new instance inherit a dead leader's
        # lease and skip the takeover resync
        self.elector = LeaderElector(
            self.store, identity=f"karpenter-{uuid.uuid4().hex[:12]}",
            clock=self.clock,
        )
        # sync mode collapses the batch window so tests drive deterministically
        batcher = (
            Batcher(self.clock, idle_duration=0.0, max_duration=0.0)
            if sync
            else Batcher(
                self.clock,
                idle_duration=self.options.batch_idle_duration,
                max_duration=self.options.batch_max_duration,
            )
        )
        self.provisioner = Provisioner(
            self.store,
            self.cloud,
            solver=solver,
            clock=self.clock,
            batcher=batcher,
            cluster=self.cluster,
            recorder=self.recorder,
            registry=self.registry,
            log=self.log.with_values(controller="provisioner"),
        )
        from karpenter_tpu.controllers.disruption import DisruptionController
        from karpenter_tpu.controllers.node.leasegc import LeaseGarbageCollectionController
        from karpenter_tpu.controllers.node.termination import NodeTerminationController
        from karpenter_tpu.controllers.nodeclaim.consistency import (
            NodeClaimConsistencyController,
        )
        from karpenter_tpu.controllers.nodeclaim.disruption import (
            NodeClaimDisruptionController,
        )
        from karpenter_tpu.controllers.nodeclaim.garbagecollection import (
            NodeClaimGarbageCollectionController,
        )
        from karpenter_tpu.controllers.nodepool.counter import NodePoolCounterController
        from karpenter_tpu.controllers.nodepool.hash import NodePoolHashController
        from karpenter_tpu.controllers.nodepool.readiness import (
            NodePoolReadinessController,
        )
        from karpenter_tpu.controllers.nodepool.validation import (
            NodePoolValidationController,
        )
        from karpenter_tpu.controllers.metrics import (
            NodeMetricsController,
            NodePoolMetricsController,
            PodMetricsController,
        )
        from karpenter_tpu.kube.daemonset import DaemonSetController
        from karpenter_tpu.kube.workload import WorkloadController

        self.controllers = [
            NodePoolHashController(self.store),
            NodePoolValidationController(self.store, recorder=self.recorder),
            NodePoolReadinessController(self.store),
            NodePoolCounterController(self.store),
            NodeClaimLifecycleController(
                self.store, self.cloud, clock=self.clock, recorder=self.recorder,
                registry=self.registry,
            ),
            NodeClaimDisruptionController(
                self.store, self.cloud, self.cluster, clock=self.clock,
                registry=self.registry,
            ),
            NodeClaimGarbageCollectionController(
                self.store, self.cloud, clock=self.clock, recorder=self.recorder
            ),
            NodeClaimConsistencyController(
                self.store, clock=self.clock, recorder=self.recorder
            ),
            NodeTerminationController(
                self.store, clock=self.clock, recorder=self.recorder,
                registry=self.registry,
            ),
            LeaseGarbageCollectionController(self.store, recorder=self.recorder),
            DaemonSetController(self.store),
            WorkloadController(self.store),
            NodeMetricsController(self.store, registry=self.registry),
            PodMetricsController(self.store, registry=self.registry),
            NodePoolMetricsController(self.store, registry=self.registry),
        ]
        self.disruption = None
        if enable_disruption:
            self.disruption = DisruptionController(
                self.store,
                self.cluster,
                self.cloud,
                self.provisioner,
                clock=self.clock,
                recorder=self.recorder,
                # feature gates feed the method ladder (spot_to_spot gate,
                # consolidation.go:214); explicit disruption_options win
                options={**self.options.feature_gates, **(disruption_options or {})},
                poll_period=0.0 if sync else 10.0,
                validation_ttl=(
                    validation_ttl if validation_ttl is not None else (0.0 if sync else 15.0)
                ),
                registry=self.registry,
                log=self.log.with_values(controller="disruption"),
            )
            self.controllers.append(self.disruption)

    def _round(self, rng=None) -> bool:
        """One reconcile round: informer-first event dispatch, then the
        poll sources (provisioner, controllers, binder). `rng` randomizes
        the poll ORDER (deflake mode); event dispatch stays informer-first
        because state must mirror an event before any controller acts on
        it (state/informer/*)."""
        was_leader = self.elector.is_leader()
        leading = self.elector.try_acquire()
        if leading and not was_leader and self.elector.last_acquire_takeover:
            # takeover: warm the informer cache from the store snapshot —
            # the hermetic store's event queue is single-consumer, so a
            # standby has not seen the events the old leader drained — and
            # arm the batcher: pod events the old leader consumed but never
            # finished reconciling must not strand pending pods. Renewing
            # our OWN stale lease (clock jumped past the duration with no
            # contender) is NOT a takeover: the holder never changed, so
            # nobody else drained events and the informer state is
            # continuous — resyncing there would journal an opaque
            # consolidation bump (and rebuild every cached snapshot) each
            # time the clock outruns the lease
            self.cluster.resync()
            self.provisioner.trigger()
        if not leading:
            return False  # standby: hold position until the lease frees
        progressed = False
        for event in self.store.drain_events():
            self.cluster.on_event(event)
            self.provisioner.on_event(event)
            for c in self.controllers:
                c.on_event(event)
            # the elector's own renewals are bookkeeping, not work: they
            # must not hold the loop out of idle (one spurious full round
            # per renewal otherwise)
            if not (event.kind == "leases"
                    and getattr(event.obj.metadata, "namespace", "") == "kube-system"):
                progressed = True
        sources = [self.provisioner.reconcile]
        sources += [c.poll for c in self.controllers]
        sources.append(self.binder.bind_pending)
        if rng is not None:
            rng.shuffle(sources)
        for poll in sources:
            if poll():
                progressed = True
        return progressed

    def run_until_idle(self, max_rounds: int = 100) -> int:
        """Drain events and reconcile until nothing changes; returns rounds."""
        rounds = 0
        for rounds in range(1, max_rounds + 1):
            if not self._round():
                break
        return rounds

    def run_until_idle_shuffled(self, rng, max_rounds: int = 100) -> int:
        """Deflake mode — the -race/flake-attempts analog (SURVEY.md §5):
        the poll order is re-randomized every round, surfacing
        order-dependent bugs the fixed reconcile order would mask. The
        Go reference gets interleaving variance from the scheduler for
        free; a single-threaded runtime has to inject it. Invariants must
        hold under EVERY ordering (tests/test_deflake.py sweeps seeds)."""
        rounds = 0
        for rounds in range(1, max_rounds + 1):
            if not self._round(rng=rng):
                break
        return rounds

    # -- convenience -----------------------------------------------------
    def create(self, kind: str, *objs):
        for obj in objs:
            self.store.create(kind, obj)
        return objs[0] if len(objs) == 1 else objs

    def provision(self, *pods):
        """Create pods → run to quiescence (the ExpectProvisioned analog)."""
        for p in pods:
            self.store.create("pods", p)
        self.run_until_idle()
        return pods
