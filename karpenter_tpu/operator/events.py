"""Event recorder: deduplicated, rate-limited k8s Events.

Mirror of the reference's pkg/events/recorder.go:47-98: identical events
within a 90 s TTL are emitted once (the dedupe cache keys on reason +
involved object + message), and a token bucket caps the global emission
rate so an event storm can't flood the apiserver. Events land in the
hermetic store's "events" kind when a store is attached, and are always
kept in a bounded in-memory ring for test assertions.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

DEDUPE_TTL = 90.0  # recorder.go:47
RATE_LIMIT_QPS = 10.0  # recorder.go flowcontrol bucket
RATE_LIMIT_BURST = 25


@dataclass
class EventRecord:
    reason: str
    message: str
    type: str = "Normal"  # Normal | Warning
    object_kind: str = ""
    object_name: str = ""
    timestamp: float = 0.0
    count: int = 1
    metadata: object = field(default=None)


class Recorder:
    def __init__(self, clock=None, store=None, keep: int = 1000):
        from karpenter_tpu.utils.clock import Clock

        self.clock = clock or Clock()
        self.store = store
        self.events: deque = deque(maxlen=keep)
        self._seen: dict = {}  # dedupe key -> (expiry, EventRecord)
        self._tokens = float(RATE_LIMIT_BURST)
        self._last_refill = self.clock.now()
        self.dropped = 0

    def publish(self, reason: str, message: str, obj=None, type: str = "Normal"):
        now = self.clock.now()
        kind = type_name(obj)
        name = getattr(getattr(obj, "metadata", None), "name", "") if obj is not None else ""
        key = (reason, kind, name, message)

        # dedupe window: repeat events bump the count on the cached record
        cached = self._seen.get(key)
        if cached is not None and cached[0] > now:
            cached[1].count += 1
            return None

        # token-bucket rate limit
        self._tokens = min(
            RATE_LIMIT_BURST, self._tokens + (now - self._last_refill) * RATE_LIMIT_QPS
        )
        self._last_refill = now
        if self._tokens < 1.0:
            self.dropped += 1
            return None
        self._tokens -= 1.0

        rec = EventRecord(
            reason=reason, message=message, type=type,
            object_kind=kind, object_name=name, timestamp=now,
        )
        self._seen[key] = (now + DEDUPE_TTL, rec)
        if len(self._seen) > 4096:  # TTL-expired entries drain lazily
            self._seen = {k: v for k, v in self._seen.items() if v[0] > now}
        self.events.append(rec)
        if self.store is not None:
            from karpenter_tpu.api.objects import ObjectMeta

            rec.metadata = ObjectMeta(
                name=f"evt-{reason.lower()}-{int(now * 1000) % 10**9}-{len(self.events)}",
                namespace="default",
            )
            try:
                self.store.create("events", rec)
            except Exception:
                pass  # events are best-effort
        return rec

    # -- test helpers (the reference's test eventrecorder double) --------
    def reasons(self) -> list:
        return [e.reason for e in self.events]

    def by_reason(self, reason: str) -> list:
        return [e for e in self.events if e.reason == reason]


def type_name(obj) -> str:
    return type(obj).__name__ if obj is not None else ""
