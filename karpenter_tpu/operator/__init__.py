from karpenter_tpu.operator.environment import Environment  # noqa: F401
