"""Operator options: flags + env fallback + feature gates.

Mirror of the reference's pkg/operator/options (options.go:83-98): every
knob has a default, an env-var fallback (KARPENTER_ prefixed, like
BoolVarWithEnv options.go:70), and a constructor override; feature gates
parse the k8s component-base "Name=bool,Name=bool" string
(options.go:128-133 — the single reference gate is SpotToSpotConsolidation,
consumed by consolidation.go:214).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.utils.envknobs import env_str


def _env(name: str, default, cast=str):
    raw = env_str(f"KARPENTER_{name}")
    if raw is None:
        return default
    if cast is bool:
        return raw.strip().lower() in ("1", "true", "yes")
    return cast(raw)


def parse_feature_gates(spec: str) -> dict:
    """"SpotToSpotConsolidation=true,Foo=false" → {snake_case: bool}."""
    gates = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid feature gate {part!r} (want Name=bool)")
        name, val = part.split("=", 1)
        key = _snake(name.strip())
        v = val.strip().lower()
        if v not in ("true", "false"):
            raise ValueError(f"invalid feature gate value {part!r}")
        gates[key] = v == "true"
    return gates


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and (not name[i - 1].isupper()):
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


@dataclass
class Options:
    # batching window (options.go:96-97)
    batch_idle_duration: float = 1.0
    batch_max_duration: float = 10.0
    # apiserver client limits (options.go:90-91)
    kube_client_qps: float = 200.0
    kube_client_burst: int = 300
    # service ports
    metrics_port: int = 8000
    # metrics bind address: "" = all interfaces (a container's Prometheus
    # scrape arrives on the pod IP); set KARPENTER_METRICS_BIND=127.0.0.1
    # for local-only exposure — the mirror of the solver service's --host
    metrics_bind_addr: str = ""
    health_probe_port: int = 8081
    # observability
    log_level: str = "info"
    enable_profiling: bool = False
    # feature gates (snake_case keys; options.go:128-133)
    feature_gates: dict = field(default_factory=lambda: {"spot_to_spot_consolidation": False})

    @classmethod
    def from_env(cls, **overrides) -> "Options":
        opts = cls(
            batch_idle_duration=_env("BATCH_IDLE_DURATION", 1.0, float),
            batch_max_duration=_env("BATCH_MAX_DURATION", 10.0, float),
            kube_client_qps=_env("KUBE_CLIENT_QPS", 200.0, float),
            kube_client_burst=_env("KUBE_CLIENT_BURST", 300, int),
            metrics_port=_env("METRICS_PORT", 8000, int),
            metrics_bind_addr=_env("METRICS_BIND", ""),
            health_probe_port=_env("HEALTH_PROBE_PORT", 8081, int),
            log_level=_env("LOG_LEVEL", "info"),
            enable_profiling=_env("ENABLE_PROFILING", False, bool),
        )
        gates = _env("FEATURE_GATES", "")
        if gates:
            opts.feature_gates.update(parse_feature_gates(gates))
        for k, v in overrides.items():
            if k == "feature_gates":
                opts.feature_gates.update(v)
            elif not hasattr(opts, k):
                raise TypeError(f"unknown option {k!r}")
            else:
                setattr(opts, k, v)
        return opts

    def gate(self, name: str) -> bool:
        return bool(self.feature_gates.get(name, False))
