"""Structured logging plane.

Mirror of the reference's pkg/operator/logging (logging.go): a leveled,
key=value structured logger (the zapr analog), a `NOP` logger used to mute
noisy paths (the reference silences its disruption simulations with
NopLogger, disruption/helpers.go:84,93), and `with_values` child loggers
carrying controller context (injection.WithControllerName analog).

Kept dependency-free on purpose: records go to stderr as single lines
(`level=info controller=provisioner msg="..." pods=12`), machine-grepable
the way production structured logs are, and a test can swap the sink.
"""

from __future__ import annotations

import sys
import threading
import time

LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}
_ALIASES = {"warning": "warn", "err": "error"}

# ambient key=value contributors consulted on every emission — the tracing
# plane (karpenter_tpu/obs) injects trace=<id> here so a grep for one round's
# trace id yields its full log slice. Providers are lowest-precedence
# (with_values context and per-call kv override them) and must be cheap;
# a raising provider is ignored rather than breaking the record.
_CONTEXT_PROVIDERS: list = []


def add_context_provider(fn) -> None:
    """Register ``fn() -> dict`` as an ambient context source."""
    if fn not in _CONTEXT_PROVIDERS:
        _CONTEXT_PROVIDERS.append(fn)


def remove_context_provider(fn) -> None:
    try:
        _CONTEXT_PROVIDERS.remove(fn)
    except ValueError:
        pass


def _resolve_level(level) -> int:
    """Normalize case and common spellings; unknown values fall back to
    info WITH a visible complaint rather than silently."""
    if isinstance(level, int):
        return level
    name = _ALIASES.get(str(level).strip().lower(), str(level).strip().lower())
    n = LEVELS.get(name)
    if n is None:
        print(f'level=warn msg="unknown log level {level!r}, using info"',
              file=sys.stderr)
        return LEVELS["info"]
    return n


def root_cause(exc: BaseException) -> str:
    """Innermost exception class name along the __cause__/__context__
    chain — the label fallback paths attribute rescues to (a bare
    ``RpcError`` says the wire broke; ``KeyError`` inside it says the
    payload did)."""
    seen = {id(exc)}
    while True:
        if exc.__cause__ is not None:
            nxt = exc.__cause__
        elif exc.__suppress_context__:
            nxt = None  # `raise X from None`: the context was disowned
        else:
            nxt = exc.__context__
        if nxt is None or id(nxt) in seen:
            return type(exc).__name__
        seen.add(id(nxt))
        exc = nxt


def _escape(v) -> str:
    s = str(v).replace("\\", "\\\\").replace('"', '\\"')
    return s.replace("\n", "\\n").replace("\r", "\\r")


def _fmt_value(v) -> str:
    """One token per value: quotes/newlines escaped so a record is always
    exactly one machine-grepable line."""
    s = _escape(v)
    return f'"{s}"' if (" " in s or s == "" or "\\" in s) else s


class Logger:
    def __init__(self, level="info", sink=None, values: dict | None = None,
                 clock=None):
        self._level = _resolve_level(level)
        self._sink = sink  # callable(str) | None = stderr
        self._values = dict(values or {})
        self._clock = clock
        self._lock = threading.Lock()

    # -- context ---------------------------------------------------------
    def with_values(self, **values) -> "Logger":
        """Child logger carrying extra key=value context (zapr .WithValues /
        the controller-name injection)."""
        child = Logger(level=self._level, sink=self._sink,
                       values={**self._values, **values}, clock=self._clock)
        child._lock = self._lock  # children share the parent's sink lock
        return child

    # -- emission --------------------------------------------------------
    def _emit(self, level: str, msg: str, kv: dict):
        if LEVELS[level] < self._level:
            return
        now = self._clock.now() if self._clock is not None else time.time()
        parts = [f"ts={now:.3f}", f"level={level}"]
        ambient: dict = {}
        for fn in _CONTEXT_PROVIDERS:
            try:
                ambient.update(fn() or {})
            except Exception:
                pass  # ambient context must never break a record
        for k, v in {**ambient, **self._values, **kv}.items():
            parts.append(f"{k}={_fmt_value(v)}")
        parts.append(f'msg="{_escape(msg)}"')
        line = " ".join(parts)
        with self._lock:
            if self._sink is not None:
                self._sink(line)
            else:
                print(line, file=sys.stderr)

    def debug(self, msg: str, **kv):
        self._emit("debug", msg, kv)

    def info(self, msg: str, **kv):
        self._emit("info", msg, kv)

    def warn(self, msg: str, **kv):
        self._emit("warn", msg, kv)

    def error(self, msg: str, **kv):
        self._emit("error", msg, kv)

    @property
    def enabled(self) -> bool:
        return True


class NopLogger(Logger):
    """Discards everything — wraps noisy paths (the reference mutes its
    disruption simulations this way, helpers.go:84)."""

    def __init__(self):
        super().__init__(level="error")

    def _emit(self, level, msg, kv):
        pass

    def with_values(self, **values) -> "NopLogger":
        return self

    @property
    def enabled(self) -> bool:
        return False


NOP = NopLogger()


def make_logger(level: str | None = None, sink=None, clock=None) -> Logger:
    """Root logger honoring Options.log_level / KARPENTER_LOG_LEVEL."""
    if level is None:
        from karpenter_tpu.utils.envknobs import env_str

        level = env_str("KARPENTER_LOG_LEVEL", "info")
    return Logger(level=level, sink=sink, clock=clock)
