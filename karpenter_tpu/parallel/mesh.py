"""Device-mesh sharding for the solve kernels: the PARTITIONED formulation.

The replicated program this module used to run (group/type tensors sharded
over the mesh, one all-gathered pack scan) made 8 devices buy nothing:
`shard.block` — the device wait on the replicated scan — was the entire
MULTICHIP number (PR-6 attribution). The pod-group axis now **genuinely
partitions** instead:

* **Partition.** `plan_shards` splits the FFD-ordered group axis into
  contiguous slices balanced by estimated bin need, one slice per mesh
  device (the mesh flattens for the pack — the scan's inner tensors are
  [B_s, T] and far too small for model-axis collectives to earn anything).
  Each shard runs the SAME jitted ``solve_step`` over its slice against a
  **per-shard bin-capacity budget** (``ShardPlan.budget``, a unified pow-2
  bucket so one executable serves every shard), so the scan's sequential
  length drops from G steps over a [B, T] state to G_s steps over
  [B_s, T] — the total scan work falls by ~the shard count even before
  any cross-device concurrency.
* **Pipeline.** Shard dispatch is async: shard k+1's host tensorize
  (slice + pad + ``device_put``) runs while shard k's program is already
  in flight — the `shard.tensorize`-under-`shard.block` overlap the
  module's TODO used to describe. The hidden host time is accounted on
  the device plane (``devplane.record_shard_overlap``).
* **Merge.** Per-shard outputs reconcile into one global bin axis
  (block-placement: shard s owns bins [s*B_s, (s+1)*B_s)); per-group
  feasibility rows concatenate exactly (F is group-local). Bin occupancy
  needs no cross-shard psum here because eligibility (below) guarantees
  shards share no mutable global state — existing-node capacity and
  finite nodepool limits, the two cross-shard accumulators that WOULD
  need reconciling, force the fallback ladder instead.
* **Repair.** Pods a shard could not place inside its budget *straddle*
  the partition: a bounded host pass (`_repair_merged`,
  ``KARPENTER_SHARD_REPAIR_MAX``) re-packs them into other shards'
  residual bin capacity (soundness-gated: only bins whose member groups'
  requirement rows are bit-equal to the straddler's, or empty, so the
  merged requirement set is decomposable and the kernel's own F ∧
  surviving-types state is exact) or opens fresh bins from the
  weight-best template with the kernel's own new-bin rule. Repair beyond
  the bound falls back to the plain unsharded solve.

**Exactness contract.** The merged end state is bit-identical to the
**unsharded oracle of the same partition**: :func:`partitioned_reference`
runs the identical per-shard ``solve_step`` sequentially on one device and
the identical merge/repair host code — tests/test_partitioned_mesh.py pins
device-vs-oracle equality across mesh shapes, and ``perf multichip``
reports it as ``parity``. On a degenerate (single-device) mesh the plan is
refused and the solve runs unsharded, so the partitioned path degrades to
exact global-oracle parity. Against the *global* sequential oracle the
partitioned pack may legitimately open more bins (a straddler the repair
pass placed on a fresh bin where the global scan would have found residual
capacity in another group's bin); the perf row reports that as node
overhead, exactly like the grid rows do.

**Fallback ladder.** Snapshots the partition cannot express keep the old
exact paths: existing nodes (cross-shard capacity), finite nodepool limits
(cross-shard budget), minValues, single-bin groups, and active topology
conflict/spread/affinity classes (cross-GROUP bin state) route to the
replicated sharded program (`_replicated_solve`, bit-identical to the
unsharded kernel — the pre-partition contract); a degenerate mesh or a
repair overflow routes to the plain unsharded solve. ``LAST_RUN`` records
which rung ran and why, and every ``sharded_solve`` call additionally
records exactly one ``("mesh.partition", rung, reason)`` verdict on the
decision ledger (:mod:`karpenter_tpu.obs.decisions` — reasons are the
refusal causes above, drawn from the site's closed enum), so a
steady-state loss of the partitioned rung fires the ``rung-regression``
trace dump instead of hiding in a diagnostics dict; ``plan_shards`` also
exports each plan's shard-balance quality (max/mean hybrid shard weight,
``karpenter_shard_balance_ratio``). See deploy/README.md "Decision
plane".

Stage attribution (obs flight recorder + devplane): ``shard.tensorize``
(per-shard host slice/pad/placement), ``shard.dispatch`` (async launch,
plus XLA compile on a cold ``mesh.shard`` ledger key — keys carry the
shard shape AND the target device, so per-device executables are visible,
not warm-looking), ``shard.block`` (the wait for all in-flight shards),
``shard.merge`` (gather + reconcile), ``shard.repair`` (the bounded host
pass). Pad waste lands per shard on ``karpenter_pad_waste_ratio
{site="mesh.shards"}``.
"""

from __future__ import annotations

import functools
import math
import threading
import time
from dataclasses import dataclass

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.utils.envknobs import env_bool, env_int
from karpenter_tpu import obs
from karpenter_tpu.obs import devplane
from karpenter_tpu.ops import kernels
from karpenter_tpu.ops.tensorize import (
    SPREAD_OWNED_MIN,
    bucket,
    shard_view,
)

DATA_AXIS = "data"
MODEL_AXIS = "model"

# straddling pods (a shard's budget ran dry) beyond this bound abandon the
# partitioned result and fall back to the unsharded solve: repair is a
# host-sequential pass, so an unbounded one could quietly become the old
# host-loop regression the device path exists to avoid
SHARD_REPAIR_MAX = 4096

# diagnostics of the last sharded_solve call, read by the perf harness's
# multichip rows (engine rung, per-shard shapes, repair/overlap totals)
class _LastRun(threading.local):
    """Dict-like facade over a per-THREAD run record: diagnostics of the
    most recent sharded solve on the calling thread (engine rung, shard
    stats, overlap, repair counts). Thread-local because the PR-7 solver
    service drives concurrent solves on gRPC worker threads — a module
    global dict would interleave two tenants' clear()/update() sequences
    and hand a reader (perf rows, the dryrun parity check) another solve's
    engine field. Single-threaded readers (perf harness, tests, dryrun)
    read right after their own solve and are unaffected."""

    def __init__(self):
        self._d: dict = {}

    def get(self, key, default=None):
        return self._d.get(key, default)

    def __getitem__(self, key):
        return self._d[key]

    def __setitem__(self, key, value):
        self._d[key] = value

    def __contains__(self, key):
        return key in self._d

    def clear(self):
        self._d.clear()

    def update(self, *args, **kw):
        self._d.update(*args, **kw)


LAST_RUN = _LastRun()


@functools.lru_cache(maxsize=32)
def _jitted_solve_step(max_bins: int, max_minv: int = 0, level_bits: int = 20):
    """One jitted executable per (max_bins, minValues width, level bits);
    jax.jit's own cache handles the per-shape/per-device/per-sharding
    specializations under it (a partitioned shard pinned to device k
    compiles its own executable — the mesh.shard ledger key carries the
    device index so those compiles are attributed, not warm-looking)."""
    return jax.jit(functools.partial(kernels.solve_step, max_bins=max_bins,
                                     use_pallas=False, max_minv=max_minv,
                                     level_bits=level_bits))


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    # squarest 2D factorization: data-parallel groups x model-parallel types
    d = int(math.sqrt(n))
    while n % d:
        d -= 1
    shape = (n // d, d)
    return Mesh(mesh_utils.create_device_mesh(shape, devs[:n]), (DATA_AXIS, MODEL_AXIS))


def make_multihost_mesh(n_hosts: int | None = None,
                        chips_per_host: int | None = None) -> Mesh:
    """DCN-tier mesh: the data (group) axis spans HOSTS and the model
    (type) axis stays INTRA-host. For the partitioned pack the layout is
    moot (shards are independent programs, no collectives); the replicated
    fallback still wants its heavy [G,T] all-gather on ICI, so the
    scaling-book placement is kept.

    On real multi-host installs, jax.devices() already interleaves
    processes and `mesh_utils` keeps each host's chips contiguous on the
    trailing axis; under xla_force_host_platform_device_count the same
    program dry-runs single-process with virtual "hosts"."""
    devs = jax.devices()
    if n_hosts is None:
        n_hosts = max(
            getattr(jax, "process_count", lambda: 1)(), 1
        )
        if n_hosts == 1:
            # virtual topology: treat the device array as 2 "hosts" when
            # it splits evenly, else fall back to the flat mesh
            n_hosts = 2 if len(devs) % 2 == 0 and len(devs) >= 4 else 1
    if chips_per_host is None:
        chips_per_host = len(devs) // n_hosts
    n = n_hosts * chips_per_host
    if n_hosts <= 1 or n == 0 or n > len(devs):
        # over-asked topology (more hosts than devices) degrades to the
        # flat single-tier mesh rather than erroring
        return make_mesh(min(max(n, 1), len(devs)))
    arr = mesh_utils.create_device_mesh(
        (n_hosts, chips_per_host), devs[:n],
    )
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def _pad_to(a: np.ndarray, axis: int, mult: int) -> np.ndarray:
    size = a.shape[axis]
    target = ((size + mult - 1) // mult) * mult
    if target == size:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, target - size)
    return np.pad(a, pad)


# --------------------------------------------------------------------------
# partition planning
# --------------------------------------------------------------------------


@dataclass
class ShardPlan:
    """One partitioned dispatch: contiguous group slices (FFD order
    preserved), a unified padded group axis, and a unified per-shard bin
    budget (one compiled executable serves every shard)."""

    bounds: list  # [(lo, hi)] group slices, contiguous and ordered
    g_pad: int  # padded per-shard group axis
    budget: int  # per-shard bin axis (pow-2/3·2^k bucket)
    need: list  # per-shard un-padded bin estimate (pad-waste accounting)

    @property
    def n_shards(self) -> int:
        return len(self.bounds)


def _partition_blockers(args: dict) -> str | None:
    """Why this snapshot cannot partition (None = eligible). Each blocker
    is a cross-shard coupling the block-diagonal merge cannot reconcile:
    existing nodes and finite limits are mutable GLOBAL accumulators,
    minValues/single-bin change the new-bin rule the repair pass mirrors,
    and topology classes are cross-GROUP bin state."""
    if "e_avail" in args:
        return "existing-nodes"
    mm = args.get("m_minv")
    if mm is not None and np.asarray(mm).size and int(np.asarray(mm).max()) > 0:
        return "min-values"
    if np.isfinite(np.asarray(args["m_limits"])).any():
        return "nodepool-limits"
    # per-group checks look only at ACTIVE rows: kernel_args pads the
    # group axis to a pow-2 bucket with fill 0, and a padded g_sown row
    # of 0 (< SPREAD_OWNED_MIN) or padded zero flags must not read as a
    # blocker — count-0 rows place no pods and are inert by the padding
    # contract, so any non-bucket-aligned real snapshot would otherwise
    # silently lose the partitioned rung
    active = np.asarray(args["g_count"]) > 0
    gs = args.get("g_single")
    if gs is not None and np.asarray(gs)[active].any():
        return "single-bin-groups"
    for k in ("g_decl", "g_match", "g_aneed", "g_amatch"):
        v = args.get(k)
        if v is not None and np.asarray(v)[active].any():
            return "topology-classes"
    sown = args.get("g_sown")
    if sown is not None and np.asarray(sown).size and (
        np.asarray(sown)[active] < SPREAD_OWNED_MIN
    ).any():
        return "topology-classes"
    return None


def _bin_need(args: dict):
    """(per-group bin-need weight [G], per-resource max allocatable [R]) —
    the same demand/allocatable lower bound the solver's bin-axis estimator
    uses (models/solver.py _run_and_decode), per group so the planner can
    balance slices and budget shards by it. The pods resource axis rides
    along (every pod demands 1), so kubelet max-pods caps the bound too."""
    g_count = np.asarray(args["g_count"]).astype(np.float64)
    g_demand = np.asarray(args["g_demand"]).astype(np.float64)
    t_alloc = np.asarray(args["t_alloc"]).astype(np.float64)
    max_alloc = t_alloc.max(axis=0) if t_alloc.size else np.zeros(0)
    demand = g_demand * g_count[:, None]
    with np.errstate(divide="ignore", invalid="ignore"):
        lb = np.where(max_alloc[None, :] > 0, demand / max_alloc[None, :], 0.0)
    return np.nan_to_num(lb).max(axis=1), max_alloc


def estimate_bin_axis(args: dict) -> int:
    """Unsharded bin-axis estimate for one solve (demand lower bound with
    the solver's 1.5x FFD headroom) — the honest baseline axis for the
    multichip comparison rows (perf/run.py), shared with shard budgeting."""
    w, _ = _bin_need(args)
    total_pods = int(np.asarray(args["g_count"]).sum())
    est = int(np.ceil(w.sum())) if w.size else 1
    return min(max(total_pods, 1), max((3 * est) // 2, 64), 4096)


def plan_shards(args: dict, n_shards: int, max_bins: int | None = None
                ) -> ShardPlan | None:
    """Partition the group axis for `n_shards` devices, or None when the
    snapshot must fall back (see `_partition_blockers` / degenerate
    shapes). KARPENTER_SHARD_PARTITION=0 disables the partitioned path
    outright (A/B against the replicated program). Every refusal records
    its actual cause in ``LAST_RUN["plan_refusal"]`` — a leaked
    kill-switch in CI must not surface as a coincidental blocker name."""
    if not env_bool("KARPENTER_SHARD_PARTITION", True):
        LAST_RUN["plan_refusal"] = "partition-disabled"
        return None
    if n_shards < 2:
        LAST_RUN["plan_refusal"] = "degenerate-mesh"
        return None
    blocker = _partition_blockers(args)
    if blocker is not None:
        LAST_RUN["plan_refusal"] = blocker
        return None
    g_count = np.asarray(args["g_count"]).astype(np.int64)
    G = int(g_count.shape[0])
    real_groups = int((g_count > 0).sum())
    total_pods = int(g_count.sum())
    if total_pods <= 0 or real_groups < 4:
        LAST_RUN["plan_refusal"] = "too-few-groups"
        return None
    S = min(n_shards, max(real_groups // 2, 1))
    if S < 2:
        LAST_RUN["plan_refusal"] = "too-few-groups"
        return None
    need_w, max_alloc = _bin_need(args)
    total_need = float(need_w.sum())
    if total_need <= 0 or not (max_alloc > 0).any():
        LAST_RUN["plan_refusal"] = "no-need"
        return None
    # contiguous slices balanced by a hybrid weight: per-shard wall clock
    # is (scan steps) x (per-step [budget, T] cost), so pure need-balance
    # piles the many small-demand FFD-tail groups onto the last shard
    # (169 of 512 in the gate shape) while pure group-balance inflates the
    # unified budget to the heaviest slice's need. need + mean(need) per
    # group bounds the step imbalance at ~2x while keeping need (and so
    # the budget) near-balanced.
    w = need_w + (g_count > 0) * (total_need / max(real_groups, 1))
    cum = np.cumsum(w)
    total = float(cum[-1])
    cuts = np.searchsorted(cum, total * np.arange(1, S) / S, side="left") + 1
    bounds = []
    lo = 0
    for c in [int(c) for c in cuts] + [G]:
        hi = min(max(c, lo), G)
        if hi > lo:
            bounds.append((lo, hi))
            lo = hi
    # the trailing [G] sentinel always extends the last slice to G, so
    # every row (incl. zero-weight padding) is covered
    assert lo == G
    if len(bounds) < 2:
        LAST_RUN["plan_refusal"] = "single-slice"
        return None
    # shard-balance quality of this plan: max/mean hybrid shard weight.
    # The hybrid weight bounds imbalance at ~2x but doesn't minimize it
    # (ROADMAP names shard balance as the next mesh lever) — the ratio is
    # its first surface (karpenter_shard_balance_ratio gauge + the
    # multichip perf rows via LAST_RUN).
    shard_w = np.array([float(w[lo:hi].sum()) for lo, hi in bounds])
    mean_w = float(shard_w.mean()) if shard_w.size else 0.0
    balance = float(shard_w.max() / mean_w) if mean_w > 0 else 1.0
    LAST_RUN["balance_ratio"] = round(balance, 4)
    devplane.record_shard_balance(balance)
    g_demand = np.asarray(args["g_demand"]).astype(np.float64)
    need = []
    for blo, bhi in bounds:
        demand = (g_demand[blo:bhi] * g_count[blo:bhi, None]).sum(axis=0)
        with np.errstate(divide="ignore", invalid="ignore"):
            lb = np.where(max_alloc > 0, demand / max_alloc, 0.0)
        est = int(np.ceil(np.nan_to_num(lb).max())) if lb.size else 1
        pods_s = int(g_count[blo:bhi].sum())
        need.append(min(max((3 * est) // 2, 8), max(pods_s, 1), 4096))
    budget = bucket(max(need), lo=8)
    if max_bins:
        budget = min(budget, bucket(max_bins, lo=8))
    g_pad = bucket(max(hi - lo for lo, hi in bounds), lo=8)
    return ShardPlan(bounds=bounds, g_pad=g_pad, budget=budget, need=need)


# --------------------------------------------------------------------------
# partitioned execution: pipelined per-shard dispatch + merge + repair
# --------------------------------------------------------------------------


def _repair_bound() -> int:
    return env_int("KARPENTER_SHARD_REPAIR_MAX", SHARD_REPAIR_MAX, minimum=0)


def _in_flight(out: dict) -> bool:
    """True while any array of an async-dispatched shard output has not
    yet materialized on its device (jax.Array.is_ready)."""
    for v in out.values():
        ready = getattr(v, "is_ready", None)
        if ready is not None and not ready():
            return True
    return False


def _solve_shards(args: dict, plan: ShardPlan, level_bits: int,
                  devices=None) -> list:
    """Dispatch every shard's solve; returns the per-shard (lazy) output
    dicts. With `devices`, shard s is placed on devices[s % len] and the
    dispatch is async — shard k+1's host tensorize overlaps shard k's
    in-flight program (the pipeline). Without devices (the reference
    replay) everything runs sequentially on the default device — same
    executable, same numerics, bit-identical outputs."""
    fn = _jitted_solve_step(plan.budget, 0, level_bits)
    T = int(np.asarray(args["t_mask"]).shape[0])
    K, W = np.asarray(args["g_mask"]).shape[1:]
    g_count = np.asarray(args["g_count"])
    outs = []
    overlap = 0.0
    shard_stats = []
    for s, (lo, hi) in enumerate(plan.bounds):
        t0 = time.perf_counter()
        with obs.span("shard.tensorize", kind="device", shard=s,
                      groups=hi - lo):
            local = shard_view(args, lo, hi, plan.g_pad)
            if devices is not None:
                dev = devices[s % len(devices)]
                local = {k: jax.device_put(np.asarray(v), dev)
                         for k, v in local.items()}
        tz = time.perf_counter() - t0
        if devices is not None and s and _in_flight(outs[-1]):
            # the previous shard's program is STILL unready after this
            # tensorize finished, so the whole window was hidden under
            # in-flight device work — genuinely pipelined overlap. A
            # program that completed before (or during) the tensorize
            # counts nothing: the signal must be able to read zero when
            # the pipeline is not actually hiding host time.
            overlap += tz
        if devices is not None:
            # actual = REAL rows only: the trailing slice absorbs the
            # snapshot's own bucket-padding (count-0) rows, which are as
            # inert as the shard pad and must count as waste, not work
            devplane.record_padding(
                "mesh.shards",
                int((g_count[lo:hi] > 0).sum()) * T * plan.need[s],
                plan.g_pad * T * plan.budget,
            )
        t0 = time.perf_counter()
        with obs.span("shard.dispatch", kind="device", shard=s):
            out = fn(local)
        dt = time.perf_counter() - t0
        if devices is not None:
            devplane.record_dispatch(
                "mesh.shard",
                ("part", plan.g_pad, plan.budget, level_bits, K, W, T,
                 s % len(devices)),
                dt,
            )
        outs.append(out)
        shard_stats.append({
            "shard": s, "groups": hi - lo,
            "pods": int(g_count[lo:hi].sum()),
            "bins": plan.budget, "bins_est": plan.need[s],
            "tensorize_ms": round(tz * 1000.0, 2),
            "dispatch_ms": round(dt * 1000.0, 2),
        })
    if devices is not None:
        devplane.record_shard_overlap(overlap)
        LAST_RUN["shards"] = shard_stats
        LAST_RUN["overlap_ms"] = round(overlap * 1000.0, 2)
    return outs


def _merge_shards(host_outs: list, plan: ShardPlan, G: int, T: int) -> dict:
    """Reconcile per-shard outputs into one global bin axis: shard s owns
    bins [s*budget, (s+1)*budget), group rows splice back to their slice,
    and F concatenates exactly (feasibility is group-local). Pure index
    bookkeeping over int32/bool — no float is recomputed, so the merge is
    bit-exact by construction on device and replay alike."""
    S = len(host_outs)
    Bu = plan.budget
    Bm = S * Bu
    assign = np.zeros((G, Bm), dtype=np.int32)
    used = np.zeros(Bm, dtype=bool)
    tmpl = np.zeros(Bm, dtype=np.int32)
    types = np.zeros((Bm, T), dtype=bool)
    F = np.zeros((G, T), dtype=bool)
    for s, ((lo, hi), out) in enumerate(zip(plan.bounds, host_outs)):
        n = hi - lo
        assign[lo:hi, s * Bu:(s + 1) * Bu] = np.asarray(out["assign"])[:n]
        used[s * Bu:(s + 1) * Bu] = np.asarray(out["used"])
        tmpl[s * Bu:(s + 1) * Bu] = np.asarray(out["tmpl"])
        types[s * Bu:(s + 1) * Bu] = np.asarray(out["types"])
        F[lo:hi] = np.asarray(out["F"])[:n]
    return {
        "assign": assign,
        "assign_e": np.zeros((G, 1), dtype=np.int32),
        "used": used,
        "tmpl": tmpl,
        "types": types,
        "F": F,
    }


_EPS = 1e-6


def _tmpl_full_rows(args: dict, g: int) -> np.ndarray:
    """[M] bool — host mirror of the kernel's tmpl_full row for group g
    (taints/custom-label admission AND template requirement overlap with
    the Intersects tolerance rule), for the repair pass's new-bin rule."""
    g_mask = np.asarray(args["g_mask"])[g]
    g_has = np.asarray(args["g_has"])[g]
    m_mask = np.asarray(args["m_mask"])
    m_has = np.asarray(args["m_has"])
    both = m_has & g_has[None, :]
    ov = ((m_mask & g_mask[None, :, :]) != 0).any(axis=2)
    g_tol = args.get("g_tol")
    m_tol = args.get("m_tol")
    if g_tol is not None and m_tol is not None:
        ov = ov | (np.asarray(m_tol) & np.asarray(g_tol)[g][None, :])
    return np.asarray(args["g_tmpl_ok"])[g] & (~both | ov).all(axis=1)


def _repair_merged(args: dict, merged: dict, plan: ShardPlan):
    """Bounded host repair of straddling pods — pods whose shard ran out
    of bin budget. Returns (merged, repaired_count) or None when the
    straddler count exceeds KARPENTER_SHARD_REPAIR_MAX (the caller falls
    back to the unsharded solve).

    Soundness: a straddler group g only joins a bin whose member groups'
    requirement rows are bit-equal to g's or empty — then the bin's merged
    requirement set decomposes per key to g's own (plus the template,
    whose compat `_tmpl_full_rows` re-checks), the kernel's surviving
    `types` state already enforces every member's constraints, and
    `F[g]` is exactly g-vs-type, so `types ∧ F[g]` is the exact joint
    candidate set — no three-way requirement or offering meet can differ.
    Capacity uses the kernel's own float32 floor(+eps) arithmetic, and
    fresh bins open from the weight-best template under the kernel's
    new-bin rule (minValues/limits are partition blockers, so neither
    applies here). The pass is deterministic numpy shared verbatim with
    :func:`partitioned_reference`, keeping device-vs-oracle bit parity
    through repair."""
    g_count = np.asarray(args["g_count"]).astype(np.int64)
    assign = merged["assign"]
    left = g_count - assign.sum(axis=1)
    total_left = int(left.sum())
    if total_left == 0:
        return merged, 0
    if total_left > _repair_bound():
        return None
    G = g_count.shape[0]
    g_demand = np.asarray(args["g_demand"], dtype=np.float32)
    g_mask = np.asarray(args["g_mask"])
    g_has = np.asarray(args["g_has"])
    g_tol = np.asarray(args["g_tol"]) if "g_tol" in args else np.zeros_like(g_has)
    t_alloc = np.asarray(args["t_alloc"], dtype=np.float32)
    t_tmpl = np.asarray(args["t_tmpl"])
    m_overhead = np.asarray(args["m_overhead"], dtype=np.float32)
    bin_cap = np.asarray(args["g_bin_cap"]) if "g_bin_cap" in args else None
    used, tmpl, types, F = (merged["used"], merged["tmpl"], merged["types"],
                            merged["F"])
    load = assign.T.astype(np.float32) @ g_demand
    load[used] += m_overhead[tmpl[used]]
    member = assign > 0
    row_empty = ~g_has.any(axis=1)
    repaired = 0
    for g in np.flatnonzero(left):
        n = int(left[g])
        d = g_demand[g]
        pos = d > 0
        tf = _tmpl_full_rows(args, g)
        # residual capacity in OTHER shards' bins, requirement-sound per
        # the decomposability gate above
        same = ((g_has == g_has[g]).all(axis=1)
                & (g_tol == g_tol[g]).all(axis=1)
                & (g_mask == g_mask[g]).reshape(G, -1).all(axis=1))
        blocked = member[~(same | row_empty)].any(axis=0)
        cand = used & ~blocked & tf[tmpl]
        idx = np.flatnonzero(cand)
        if idx.size and pos.any():
            adp = t_alloc[:, pos] / d[pos]  # [T,Rp]
            ldp = load[idx][:, pos] / d[pos]  # [C,Rp]
            cap_bt = np.floor(
                (adp[None, :, :] - ldp[:, None, :]).min(axis=2) + _EPS
            ).astype(np.int64)
            tok = types[idx] & F[g][None, :]
            cap_bt = np.where(tok, np.maximum(cap_bt, 0), 0)
            q = cap_bt.max(axis=1)
            for j, b in enumerate(idx):
                if n <= 0:
                    break
                room = int(q[j])
                if bin_cap is not None:
                    room = min(room, int(bin_cap[g]) - int(assign[g, b]))
                take = min(n, room)
                if take <= 0:
                    continue
                assign[g, b] += take
                load[b] += take * d
                types[b] = tok[j] & (cap_bt[j] >= take)
                member[g, b] = True
                n -= take
                repaired += take
        if n > 0 and pos.any():
            # fresh bins from the weight-best template (templates are
            # pre-sorted by weight, so the first feasible index wins —
            # the kernel's argmax-over-feasible rule)
            free_idx = np.flatnonzero(~used)
            if not free_idx.size:
                # the merged axis is exactly S x budget and every bin is
                # occupied (under-budgeted shards — e.g. one pinned type
                # per group defeats the resource lower bound): GROW the
                # axis host-side. One new column per remaining pod bounds
                # the growth by the repair budget; unused rows stay
                # used=False for the decoder, and the reference replay
                # shares this code verbatim so bit parity holds.
                assign = np.concatenate(
                    [assign, np.zeros((G, n), assign.dtype)], axis=1)
                member = np.concatenate(
                    [member, np.zeros((G, n), bool)], axis=1)
                used = np.concatenate([used, np.zeros(n, used.dtype)])
                tmpl = np.concatenate([tmpl, np.zeros(n, tmpl.dtype)])
                types = np.concatenate(
                    [types, np.zeros((n, types.shape[1]), types.dtype)])
                load = np.concatenate(
                    [load, np.zeros((n, load.shape[1]), load.dtype)])
                merged.update(assign=assign, used=used, tmpl=tmpl,
                              types=types)
                free_idx = np.flatnonzero(~used)
            if free_idx.size:
                for m in range(m_overhead.shape[0]):
                    if not tf[m]:
                        continue
                    ovh_ok = (m_overhead[m][None, :] <= t_alloc + _EPS
                              ).all(axis=1)
                    fresh = t_alloc - m_overhead[m][None, :]
                    fr = np.floor(
                        (fresh[:, pos] / d[pos]).min(axis=1) + _EPS
                    ).astype(np.int64)
                    ok_t = F[g] & (t_tmpl == m) & ovh_ok & (fr > 0)
                    if not ok_t.any():
                        continue
                    per_node = int(fr[ok_t].max())
                    if bin_cap is not None:
                        per_node = min(per_node, int(bin_cap[g]))
                    if per_node <= 0:
                        continue
                    for b in free_idx:
                        if n <= 0:
                            break
                        take = min(n, per_node)
                        used[b] = True
                        tmpl[b] = m
                        assign[g, b] = take
                        load[b] = m_overhead[m] + take * d
                        types[b] = ok_t & (fr >= take)
                        member[g, b] = True
                        n -= take
                        repaired += take
                    break
        # any residual stays unplaced — the decoder routes it to retry
        # exactly as it does for the unsharded kernel's spill
    return merged, repaired


def _partitioned_solve(mesh: Mesh, args: dict, max_bins: int,
                       level_bits: int, plan: ShardPlan):
    """Run the plan over the mesh's (flattened) devices; returns the
    merged+repaired host dict, or None when repair exceeded its bound."""
    devices = list(mesh.devices.reshape(-1))
    G = int(np.asarray(args["g_count"]).shape[0])
    T = int(np.asarray(args["t_mask"]).shape[0])
    outs = _solve_shards(args, plan, level_bits, devices=devices)
    with obs.span("shard.block", kind="device", engine="mesh",
                  shards=plan.n_shards):
        for out in outs:
            out["used"].block_until_ready()
    with obs.span("shard.merge", kind="device", engine="mesh"):
        keys = ("assign", "used", "tmpl", "F", "types")
        host_outs = [jax.device_get({k: o[k] for k in keys}) for o in outs]
        merged = _merge_shards(host_outs, plan, G, T)
    with obs.span("shard.repair", shards=plan.n_shards):
        repaired = _repair_merged(args, merged, plan)
    if repaired is None:
        return None
    merged, n_rep = repaired
    if n_rep:
        devplane.record_shard_repair(n_rep)
    LAST_RUN["repaired_pods"] = n_rep
    return merged


def partitioned_reference(args: dict, max_bins: int, n_shards: int,
                          level_bits: int = 20):
    """The unsharded oracle of the partitioned program: the SAME plan, the
    SAME per-shard ``solve_step`` executed sequentially on the default
    device, the SAME merge and repair host code. The mesh execution must
    be bit-identical to this (tests/test_partitioned_mesh.py); returns
    None when the snapshot would not partition (callers then compare
    against the plain unsharded kernel instead)."""
    plan = plan_shards(args, n_shards, max_bins)
    if plan is None:
        return None
    G = int(np.asarray(args["g_count"]).shape[0])
    T = int(np.asarray(args["t_mask"]).shape[0])
    outs = _solve_shards(args, plan, level_bits, devices=None)
    keys = ("assign", "used", "tmpl", "F", "types")
    host_outs = [jax.device_get({k: o[k] for k in keys}) for o in outs]
    merged = _merge_shards(host_outs, plan, G, T)
    repaired = _repair_merged(args, merged, plan)
    if repaired is None:
        return None
    return repaired[0]


# --------------------------------------------------------------------------
# the replicated program (exact fallback for inexpressible snapshots)
# --------------------------------------------------------------------------


def _replicated_solve(mesh: Mesh, args: dict, max_bins: int,
                      level_bits: int = 20):
    """The pre-partition sharded program: feasibility inputs sharded over
    the mesh, the pack scan consuming the all-gathered F replicated. Kept
    as the exact fallback for snapshots the partition cannot express
    (existing nodes, finite limits, topology classes, minValues) — its
    answer is bit-identical to the unsharded kernel, which is exactly the
    contract those paths already rely on. Returns lazily; consume via
    :func:`sharded_solve_host`."""
    n_data, n_model = mesh.devices.shape

    def shard(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    # pad shardable axes to multiples of their mesh axis
    args = dict(args)
    G = np.asarray(args["g_count"]).shape[0]
    args.setdefault("g_bin_cap", np.full(G, 1 << 30, dtype=np.int32))
    args.setdefault("g_single", np.zeros(G, dtype=bool))
    args.setdefault("g_decl", np.zeros((G, 1), dtype=np.uint32))
    args.setdefault("g_match", np.zeros((G, 1), dtype=np.uint32))
    args.setdefault("g_sown", np.full((G, 1), 1 << 30, dtype=np.int32))
    args.setdefault("g_smatch", np.zeros((G, 1), dtype=bool))
    args.setdefault("g_aneed", np.zeros((G, 1), dtype=bool))
    args.setdefault("g_amatch", np.zeros((G, 1), dtype=bool))
    # padded group rows are inert everywhere: count 0 means they never take
    # (a zero-filled g_sown row reads as cap 0, which only gates that row)
    G_NAMES = ["g_mask", "g_has", "g_demand", "g_count", "g_zone_allowed",
               "g_ct_allowed", "g_tmpl_ok", "g_bin_cap", "g_single",
               "g_decl", "g_match", "g_sown", "g_smatch", "g_aneed", "g_amatch"]
    T_NAMES = ["t_mask", "t_has", "t_alloc", "t_cap", "t_tmpl",
               "off_zone", "off_ct", "off_avail", "off_price"]
    if "g_tol" in args:
        G_NAMES.append("g_tol")
    if "t_tol" in args:
        T_NAMES.append("t_tol")
    # existing-node tensors: ge_ok rides the group axis; the per-node state
    # is scan-carried and stays replicated
    REPL_NAMES = ["m_mask", "m_has", "m_overhead", "m_limits"]
    if "m_minv" in args:
        REPL_NAMES.append("m_minv")
    if "m_tol" in args:
        REPL_NAMES.append("m_tol")
    if "ge_ok" in args:
        G_NAMES.append("ge_ok")
    REPL_NAMES += [k for k in ("e_avail", "e_npods", "e_scnt", "e_decl", "e_match",
                               "e_aff")
                   if k in args]
    T0 = np.asarray(args["t_mask"]).shape[0]
    with obs.span("shard.pad", n_data=n_data, n_model=n_model):
        for name in G_NAMES:
            args[name] = _pad_to(np.asarray(args[name]), 0, n_data)
        for name in T_NAMES:
            args[name] = _pad_to(np.asarray(args[name]), 0, n_model)
    Gp = args["g_count"].shape[0]
    Tp = args["t_mask"].shape[0]
    devplane.record_padding("mesh.shards", G * T0, Gp * Tp)

    # host→device placement of the shard tensors
    with obs.span("shard.tensorize", kind="device", groups=Gp, types=Tp):
        placed = dict(args)
        for name in G_NAMES:
            placed[name] = shard(args[name], P(DATA_AXIS, *([None] * (np.asarray(args[name]).ndim - 1))))
        for name in T_NAMES:
            placed[name] = shard(args[name], P(MODEL_AXIS, *([None] * (np.asarray(args[name]).ndim - 1))))
        for name in REPL_NAMES:
            placed[name] = shard(np.asarray(args[name]), P())

    max_minv = int(np.asarray(args["m_minv"]).max()) if "m_minv" in args else 0
    # the key mirrors the compiled program's real shape dims: the resource
    # axis (R) and mask widths recompile even when the padded G/T do not
    key = (max_bins, max_minv, level_bits, n_data, n_model, Gp, Tp,
           args["g_mask"].shape[1:], np.asarray(args["g_demand"]).shape[1],
           int("e_avail" in args))
    t0 = time.perf_counter()
    with mesh:
        with obs.span("shard.dispatch", kind="device", n_data=n_data,
                      n_model=n_model, bins=max_bins):
            out = _jitted_solve_step(max_bins, max_minv, level_bits)(placed)
    devplane.record_dispatch("mesh.shard", key, time.perf_counter() - t0)
    return out


# --------------------------------------------------------------------------
# public entry points
# --------------------------------------------------------------------------


def sharded_solve(mesh: Mesh, args: dict, max_bins: int, level_bits: int = 20):
    """Full solve step over the mesh. Routing ladder (module docstring):

    1. **partitioned** — the group axis splits into per-device shards,
       each packing against its own bin budget; merged + repaired host
       dict (numpy, already consumed).
    2. **replicated** — snapshots the partition cannot express (existing
       nodes, finite limits, topology classes, minValues, single-bin
       groups) run the old sharded program, bit-identical to the
       unsharded kernel; returned lazily.
    3. **unsharded** — degenerate mesh or repair-bound overflow runs the
       plain jitted kernel.

    Either return shape is consumable via :func:`sharded_solve_host`
    (numpy dicts pass through; lazy dicts block + gather)."""
    from karpenter_tpu.obs import decisions

    LAST_RUN.clear()
    n_devices = int(mesh.devices.size)
    if n_devices <= 1:
        LAST_RUN.update(engine="unsharded", reason="degenerate-mesh")
        decisions.record_decision("mesh.partition", "unsharded",
                                  "degenerate-mesh")
        max_minv = (int(np.asarray(args["m_minv"]).max())
                    if "m_minv" in args else 0)
        return _jitted_solve_step(max_bins, max_minv, level_bits)(args)
    plan = plan_shards(args, n_devices, max_bins)
    if plan is None:
        # plan_shards recorded WHY (blocker name, kill-switch, degenerate
        # shape) — no second blocker scan over the group tensors here
        LAST_RUN.update(engine="replicated",
                        reason=LAST_RUN.get("plan_refusal", "no-plan"))
        decisions.record_decision("mesh.partition", "replicated",
                                  LAST_RUN.get("reason", "no-plan"))
        return _replicated_solve(mesh, args, max_bins, level_bits)
    LAST_RUN.update(engine="partitioned", n_shards=plan.n_shards,
                    budget=plan.budget, g_pad=plan.g_pad)
    merged = _partitioned_solve(mesh, args, max_bins, level_bits, plan)
    if merged is None:
        # straddlers beyond the repair bound: the partitioned answer is
        # abandoned for the exact unsharded solve (bounded occurrence —
        # budgets carry 1.5x headroom, so this is the adversarial tail)
        LAST_RUN.update(engine="unsharded", reason="repair-bound")
        devplane.record_shard_fallback("repair-bound")
        decisions.record_decision("mesh.partition", "unsharded",
                                  "repair-bound")
        return _jitted_solve_step(max_bins, 0, level_bits)(args)
    decisions.record_decision("mesh.partition", "partitioned")
    return merged


def sharded_solve_host(mesh: Mesh, args: dict, max_bins: int,
                       level_bits: int = 20) -> dict:
    """Sharded solve consumed to host numpy: ``shard.block`` waits for any
    in-flight program, ``shard.merge`` gathers to one host dict — the
    consumption half of the shard-stage decomposition (models/solver.py
    rides this on the mesh path; the perf harness's multichip rows read
    the same leaves). The partitioned rung returns an already-merged host
    dict, so both spans are ~zero there and the real block/merge/repair
    cost sits in the rung's own leaves."""
    # late-bound through the package attribute so a test double installed
    # on karpenter_tpu.parallel.sharded_solve intercepts this path too
    from karpenter_tpu import parallel as _parallel

    out = _parallel.sharded_solve(mesh, args, max_bins,
                                  level_bits=level_bits)
    with obs.span("shard.block", kind="device", engine="mesh"):
        try:
            out["used"].block_until_ready()
        except AttributeError:
            pass  # already host-side (partitioned rung or mocked path)
    with obs.span("shard.merge", kind="device", engine="mesh"):
        host = jax.device_get(
            {k: out[k] for k in ("assign", "assign_e", "used", "tmpl", "F")}
        )
    # replay capture (obs/capsule.py, seam mesh.solve): the mesh solve's
    # exact inputs/outputs + rung + shard count. The partitioned rung
    # replays through partitioned_reference — bit-identical to this
    # execution by the module's exactness contract — which is what makes
    # "capture on the ICI mesh, replay on a one-chip dev box" work; the
    # replicated/unsharded rungs replay through the plain kernel (same
    # contract). models/solver.py skips its own solver.invoke capture on
    # the mesh rung so one dispatch yields one capture.
    from karpenter_tpu.obs import capsule as _capsule

    _capsule.record_capture(
        "mesh.solve", args, host,
        engine=LAST_RUN.get("engine"),
        reason=LAST_RUN.get("reason"),
        max_bins=max_bins, level_bits=level_bits,
        n_shards=int(mesh.devices.size),
        balance_ratio=LAST_RUN.get("balance_ratio"),
        repaired_pods=LAST_RUN.get("repaired_pods"),
    )
    return host
