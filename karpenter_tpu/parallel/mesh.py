"""Device-mesh sharding for the solve kernels.

The reference scales by bounding problem size per solve (SURVEY.md §5
long-context note); the TPU build scales by sharding the feasibility tensor
over a mesh instead: pod-groups ride the `data` axis and instance types the
`model` axis, XLA inserting the all-gathers needed before the (small,
sequential) pack scan. On real hardware those collectives ride ICI; the
same program dry-runs on a virtual CPU mesh (tests/conftest.py,
__graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import functools
import math
import time

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu import obs
from karpenter_tpu.obs import devplane
from karpenter_tpu.ops import kernels

DATA_AXIS = "data"
MODEL_AXIS = "model"


@functools.lru_cache(maxsize=32)
def _jitted_solve_step(max_bins: int, max_minv: int = 0, level_bits: int = 20):
    """One jitted executable per (max_bins, minValues width, level bits);
    jax.jit's own cache handles the per-shape/per-sharding specializations
    under it."""
    return jax.jit(functools.partial(kernels.solve_step, max_bins=max_bins,
                                     use_pallas=False, max_minv=max_minv,
                                     level_bits=level_bits))


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    # squarest 2D factorization: data-parallel groups x model-parallel types
    d = int(math.sqrt(n))
    while n % d:
        d -= 1
    shape = (n // d, d)
    return Mesh(mesh_utils.create_device_mesh(shape, devs[:n]), (DATA_AXIS, MODEL_AXIS))


def make_multihost_mesh(n_hosts: int | None = None,
                        chips_per_host: int | None = None) -> Mesh:
    """DCN-tier mesh: the data (group) axis spans HOSTS and the model
    (type) axis stays INTRA-host, so the heavy collective — the [G,T]
    feasibility all-gather feeding the pack scan — rides ICI while only
    the group-sharded inputs cross DCN (the scaling-book layout: put the
    bandwidth-hungry axis on the fast interconnect).

    On real multi-host installs, jax.devices() already interleaves
    processes and `mesh_utils` keeps each host's chips contiguous on the
    trailing axis; under xla_force_host_platform_device_count the same
    program dry-runs single-process with virtual "hosts"."""
    devs = jax.devices()
    if n_hosts is None:
        n_hosts = max(
            getattr(jax, "process_count", lambda: 1)(), 1
        )
        if n_hosts == 1:
            # virtual topology: treat the device array as 2 "hosts" when
            # it splits evenly, else fall back to the flat mesh
            n_hosts = 2 if len(devs) % 2 == 0 and len(devs) >= 4 else 1
    if chips_per_host is None:
        chips_per_host = len(devs) // n_hosts
    n = n_hosts * chips_per_host
    if n_hosts <= 1 or n == 0 or n > len(devs):
        # over-asked topology (more hosts than devices) degrades to the
        # flat single-tier mesh rather than erroring
        return make_mesh(min(max(n, 1), len(devs)))
    arr = mesh_utils.create_device_mesh(
        (n_hosts, chips_per_host), devs[:n],
    )
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def _pad_to(a: np.ndarray, axis: int, mult: int) -> np.ndarray:
    size = a.shape[axis]
    target = ((size + mult - 1) // mult) * mult
    if target == size:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, target - size)
    return np.pad(a, pad)


def sharded_solve(mesh: Mesh, args: dict, max_bins: int, level_bits: int = 20):
    """Full solve step (feasibility + pack) with the feasibility inputs
    sharded over the mesh. Returns the same outputs as the unsharded path
    (lazily — consume via :func:`sharded_solve_host` for the host dict).

    Sharding layout: group-axis tensors are split over `data`, type-axis
    tensors over `model`; the pack scan consumes the all-gathered F (XLA
    inserts the collectives) and runs replicated — it is O(G*B*T) and tiny
    next to feasibility at scale.

    Stage attribution (obs flight recorder, same ``kind=device``
    convention as ``solve.kernel``): ``shard.pad`` is the host pow-2/mesh
    padding, ``shard.tensorize`` the host→device placement of the shard
    tensors, ``shard.dispatch`` the sharded program launch (plus XLA
    compile on a cold ``mesh.shard`` ledger family). The consume side
    (``shard.block``/``shard.merge``) lives in ``sharded_solve_host`` —
    together these leaves decompose the MULTICHIP wall clock that used to
    be one opaque number.
    """
    n_data, n_model = mesh.devices.shape

    def shard(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    # pad shardable axes to multiples of their mesh axis
    args = dict(args)
    G = np.asarray(args["g_count"]).shape[0]
    args.setdefault("g_bin_cap", np.full(G, 1 << 30, dtype=np.int32))
    args.setdefault("g_single", np.zeros(G, dtype=bool))
    args.setdefault("g_decl", np.zeros((G, 1), dtype=np.uint32))
    args.setdefault("g_match", np.zeros((G, 1), dtype=np.uint32))
    args.setdefault("g_sown", np.full((G, 1), 1 << 30, dtype=np.int32))
    args.setdefault("g_smatch", np.zeros((G, 1), dtype=bool))
    args.setdefault("g_aneed", np.zeros((G, 1), dtype=bool))
    args.setdefault("g_amatch", np.zeros((G, 1), dtype=bool))
    # padded group rows are inert everywhere: count 0 means they never take
    # (a zero-filled g_sown row reads as cap 0, which only gates that row)
    G_NAMES = ["g_mask", "g_has", "g_demand", "g_count", "g_zone_allowed",
               "g_ct_allowed", "g_tmpl_ok", "g_bin_cap", "g_single",
               "g_decl", "g_match", "g_sown", "g_smatch", "g_aneed", "g_amatch"]
    T_NAMES = ["t_mask", "t_has", "t_alloc", "t_cap", "t_tmpl",
               "off_zone", "off_ct", "off_avail", "off_price"]
    if "g_tol" in args:
        G_NAMES.append("g_tol")
    if "t_tol" in args:
        T_NAMES.append("t_tol")
    # existing-node tensors: ge_ok rides the group axis; the per-node state
    # is scan-carried and stays replicated
    REPL_NAMES = ["m_mask", "m_has", "m_overhead", "m_limits"]
    if "m_minv" in args:
        REPL_NAMES.append("m_minv")
    if "m_tol" in args:
        REPL_NAMES.append("m_tol")
    if "ge_ok" in args:
        G_NAMES.append("ge_ok")
    REPL_NAMES += [k for k in ("e_avail", "e_npods", "e_scnt", "e_decl", "e_match",
                               "e_aff")
                   if k in args]
    T0 = np.asarray(args["t_mask"]).shape[0]
    with obs.span("shard.pad", n_data=n_data, n_model=n_model):
        for name in G_NAMES:
            args[name] = _pad_to(np.asarray(args[name]), 0, n_data)
        for name in T_NAMES:
            args[name] = _pad_to(np.asarray(args[name]), 0, n_model)
    Gp = args["g_count"].shape[0]
    Tp = args["t_mask"].shape[0]
    devplane.record_padding("mesh.shards", G * T0, Gp * Tp)

    # host→device placement of the shard tensors: the stage the MULTICHIP
    # overlap work (tensorize shard k+1 while shard k solves) will hide
    with obs.span("shard.tensorize", kind="device", groups=Gp, types=Tp):
        placed = dict(args)
        for name in G_NAMES:
            placed[name] = shard(args[name], P(DATA_AXIS, *([None] * (np.asarray(args[name]).ndim - 1))))
        for name in T_NAMES:
            placed[name] = shard(args[name], P(MODEL_AXIS, *([None] * (np.asarray(args[name]).ndim - 1))))
        for name in REPL_NAMES:
            placed[name] = shard(np.asarray(args[name]), P())

    max_minv = int(np.asarray(args["m_minv"]).max()) if "m_minv" in args else 0
    # the key mirrors the compiled program's real shape dims: the resource
    # axis (R) and mask widths recompile even when the padded G/T do not
    key = (max_bins, max_minv, level_bits, n_data, n_model, Gp, Tp,
           args["g_mask"].shape[1:], np.asarray(args["g_demand"]).shape[1],
           int("e_avail" in args))
    t0 = time.perf_counter()
    with mesh:
        with obs.span("shard.dispatch", kind="device", n_data=n_data,
                      n_model=n_model, bins=max_bins):
            out = _jitted_solve_step(max_bins, max_minv, level_bits)(placed)
    devplane.record_dispatch("mesh.shard", key, time.perf_counter() - t0)
    return out


def sharded_solve_host(mesh: Mesh, args: dict, max_bins: int,
                       level_bits: int = 20) -> dict:
    """Sharded solve consumed to host numpy: ``shard.block`` waits for the
    in-flight sharded program, ``shard.merge`` gathers the replicated
    outputs across the mesh into one host dict — the consumption half of
    the shard-stage decomposition (models/solver.py rides this on the
    mesh path; the perf harness's multichip row reads the same leaves)."""
    # late-bound through the package attribute so a test double installed
    # on karpenter_tpu.parallel.sharded_solve intercepts this path too
    from karpenter_tpu import parallel as _parallel

    out = _parallel.sharded_solve(mesh, args, max_bins,
                                  level_bits=level_bits)
    with obs.span("shard.block", kind="device", engine="mesh"):
        try:
            out["used"].block_until_ready()
        except AttributeError:
            pass  # already host-side (mocked path)
    with obs.span("shard.merge", kind="device", engine="mesh"):
        return jax.device_get(
            {k: out[k] for k in ("assign", "assign_e", "used", "tmpl", "F")}
        )
