from karpenter_tpu.parallel.mesh import make_mesh, sharded_solve  # noqa: F401
