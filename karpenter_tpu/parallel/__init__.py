from karpenter_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    make_multihost_mesh,
    sharded_solve,
    sharded_solve_host,
)

__all__ = ["make_mesh", "make_multihost_mesh", "sharded_solve",
           "sharded_solve_host"]
