"""gRPC solver service: the device plane as a standalone, multi-tenant
fleet service.

Wire contract (raw-bytes unary RPCs, no generated stubs):

- ``/karpenter.Solver/Solve`` — the stateless seam (PR 6): request is an
  .npz archive of the kernel's tensor snapshot (the exact args dict
  ``TPUSolver._invoke`` builds) plus a ``__meta__`` JSON entry carrying
  the static solve parameters (max_bins, level_bits, max_minv); response
  is an .npz archive of the kernel outputs (assign/assign_e/used/tmpl/F).
- ``/karpenter.Solver/Register`` — open a tenant session: meta
  ``{tenant}`` in, ``{session, ttl_s, inflight}`` out.
- ``/karpenter.Solver/SessionSolve`` — the streaming delta protocol
  (deploy/README.md "Multi-tenant solver service"): the first request of a
  session ships ``mode=full`` (the whole snapshot, optionally compressed
  under ``KARPENTER_SOLVER_COMPRESS``); every later round ships
  ``mode=delta`` — only the arrays that changed, row-spliced
  (``<key>//rows`` + ``<key>//vals``) where the leading axis moved
  sparsely — plus the cluster journal window
  (``state/cluster.py Cluster.export_deltas``) as provenance. The server
  maintains the per-tenant bundle (service/session.py) with the same
  in-place row-splice primitive the in-process disruption snapshot uses,
  and demands a full resync (FAILED_PRECONDITION, class name in the
  status details) on a journal gap, an opaque entry, an evicted bundle,
  or a patch whose shapes mismatch the cached family; out-of-order seqs
  are rejected outright. The client keys its session state per shape
  family (every array's name/shape/dtype) — a solve mix that alternates
  families (provisioning vs confirm sub-solves, the doubled bin axis)
  holds one session per family and rides deltas on each, instead of
  re-shipping the world on every flip.

The server executes on whatever backend its process sees — the tunneled
TPU in production (`python -m karpenter_tpu.service.solver_service`), CPU
or the C++ engine elsewhere — while the client process needs no jax at
dispatch time. Concurrent same-shape solves (any mix of tenants) fold
into one vmapped device dispatch under the coalescing window
(service/coalesce.py, ``KARPENTER_COALESCE_WINDOW_MS``), and per-tenant
admission budgets (``KARPENTER_TENANT_INFLIGHT``) convert overload into
backpressure instead of unbounded queueing.

Cross-boundary SLO tracing (deploy/README.md "Device-plane & SLO
telemetry"): the client threads its open round's trace id through the
``__meta__`` payload (`trace_id`), and the server opens one linked round
trace per request (`solver-service`, `client_trace=<id>`,
`tenant=<id>` on session solves) so a grep for the client's trace id
finds both halves of the hop. Request durations feed
``karpenter_solver_request_seconds{outcome}`` plus the rolling-quantile/
error-budget SLO tracker (obs/devplane.py) — tenant-labeled on session
solves — that the metrics server's ``/slo`` endpoint snapshots; a
server-side solve failure aborts the RPC with the root-cause exception
class in the status details, which the client surfaces as the ``reason``
label on ``karpenter_solver_remote_fallbacks_total`` and in its
structured warning.
"""

from __future__ import annotations

import io
import json
from collections import OrderedDict

import numpy as np

from karpenter_tpu.utils.envknobs import env_str

_METHOD = "/karpenter.Solver/Solve"
_METHOD_REGISTER = "/karpenter.Solver/Register"
_METHOD_SESSION = "/karpenter.Solver/SessionSolve"
_MAX_MSG = 256 * 1024 * 1024  # the 50k snapshot is ~tens of MB uncompressed
_GRPC_OPTS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
]

# the zstd frame magic (RFC 8878): a compressed payload is detected by
# prefix, so the wire needs no codec negotiation
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"


def _env_codec() -> str | None:
    """KARPENTER_SOLVER_COMPRESS: off by default; ``1``/``npz``/``deflate``
    compresses full-snapshot payloads with numpy's deflate zip
    (savez_compressed — transparent to np.load); ``zstd`` uses zstandard
    when importable CLIENT-side, falling back to deflate (the container
    bakes no new deps). Decompression happens SERVER-side: session
    clients learn the server's codecs at Register and downgrade to
    deflate when the server can't read zstd frames; the stateless Solve
    path has no handshake, so only use zstd there when both images carry
    zstandard."""
    from karpenter_tpu.service.session import env_bool

    v = (env_str("KARPENTER_SOLVER_COMPRESS", "") or "").strip().lower()
    if not env_bool("KARPENTER_SOLVER_COMPRESS", False):
        return None
    if v == "zstd":
        try:
            import zstandard  # noqa: F401

            return "zstd"
        except ImportError:
            return "deflate"
    return "deflate"


def _server_codecs() -> list:
    """Codecs this process can DECODE (the Register handshake's body)."""
    out = ["deflate"]
    try:
        import zstandard  # noqa: F401

        out.append("zstd")
    except ImportError:
        pass
    return out


def _pack(arrays: dict, meta: dict, codec: str | None = None) -> bytes:
    buf = io.BytesIO()
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    if codec == "deflate":
        np.savez_compressed(buf, **payload)
    else:
        np.savez(buf, **payload)
    blob = buf.getvalue()
    if codec == "zstd":
        import zstandard

        blob = zstandard.ZstdCompressor().compress(blob)
    return blob


def _unpack(blob: bytes) -> tuple:
    if blob[:4] == _ZSTD_MAGIC:
        try:
            import zstandard
        except ImportError as e:
            # name the misconfiguration instead of a bare ImportError: the
            # peer compressed with zstd this process cannot read
            raise RuntimeError(
                "zstd-compressed payload but the zstandard package is not "
                "importable here (KARPENTER_SOLVER_COMPRESS=zstd needs it "
                "on BOTH sides; session clients auto-downgrade via the "
                "Register handshake)") from e
        blob = zstandard.ZstdDecompressor().decompress(blob)
    with np.load(io.BytesIO(blob)) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode()) if "__meta__" in z.files else {}
    return arrays, meta


def _env_latency_slo() -> float | None:
    """KARPENTER_SOLVER_SLO_MS: per-request latency objective in ms
    (unset = error-only SLO)."""
    v = (env_str("KARPENTER_SOLVER_SLO_MS", "") or "").strip()
    if not v:
        return None
    try:
        return float(v) / 1000.0
    except ValueError:
        return None


class _SolverHandler:
    """Server-side execution through the solver's own `_invoke` stack: the
    shared jitted packed kernel (one compile per shape bucket, one
    device→host pull) and the calibrated small-batch native routing both
    apply on the serving side exactly as in-process. Every request runs as
    one linked round trace and lands in the service SLO tracker; session
    solves additionally ride the per-tenant snapshot cache, the
    coalescer, and the admission budget."""

    def __init__(self, use_native: bool = False, registry=None):
        from karpenter_tpu.models.solver import NativeSolver, TPUSolver
        from karpenter_tpu.obs import devplane
        from karpenter_tpu.operator import metrics as _metrics
        from karpenter_tpu.service.coalesce import Coalescer, coalesce_window_s
        from karpenter_tpu.service.session import SessionRegistry

        self._solver = NativeSolver() if use_native else TPUSolver()
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._slo = devplane.slo_tracker(
            "solver_service", latency_slo=_env_latency_slo()
        )
        self.sessions = SessionRegistry()
        # sweep-driven session GC: a periodic sweep releases expired
        # sessions' bundle bytes from the LRU budget instead of waiting
        # for a client access to trip the reap (an idle expired tenant
        # would otherwise squat its multi-MB bundle for as long as nobody
        # touched the server). KARPENTER_SESSION_SWEEP_S=0 disables.
        self._sweeper_stop = self.sessions.start_sweeper(
            registry=self._registry)
        window = coalesce_window_s()
        self._coalescer = None
        self._cpu_pool = None
        if window > 0 and not use_native:
            from concurrent.futures import ThreadPoolExecutor

            # CPU-path fan-out pool for coalesced windows, built once: a
            # fresh executor per batch would put thread spawn/join churn
            # on the serving hot path the coalescer exists to bound (the
            # pool's threads spawn lazily, so an accelerated server that
            # never takes the CPU branch pays only for this object)
            self._cpu_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="solver-cpu-fold")
            # folding needs the vmapped XLA batch kernel; the pure-native
            # server keeps per-request dispatch (its engine is a
            # sequential loop — stacking buys nothing)
            self._coalescer = Coalescer(
                dispatch_one=self._dispatch_one,
                dispatch_many=self._dispatch_many,
                window_s=window,
                registry=self._registry,
            )

    # -- dispatch (shared by Solve and SessionSolve) ---------------------

    def _dispatch_one(self, item: dict):
        out = self._solver._invoke(
            item["args"], item["key"], item["max_bins"])
        # the engine THIS dispatch ran, read on the dispatching thread
        # (the solver's engine slot is thread-local): the replay capture
        # must never stamp another tenant's rung onto this item
        item["engine"] = self._solver._last_engine
        return out

    def _dispatch_many(self, items: list):
        from karpenter_tpu.models.solver import (
            _accelerated_backend,
            batched_invoke,
        )

        # backend-aware, mirroring the solver's routing stance: on a real
        # accelerator the fold rides ONE vmapped dispatch (the compile
        # family the window exists to share); on a plain-CPU backend the
        # vmap is an emulation that loses to the per-request engine at
        # every size (KARPENTER_ASSUME_ACCELERATOR=0/1 overrides, as
        # everywhere) — the window still bounds and batches the queue, and
        # the members dispatch concurrently (the native engine's ctypes
        # call releases the GIL, so a k-fold costs ~1 solve on k cores,
        # not k sequential solves for the last member)
        if not _accelerated_backend():
            if len(items) == 1:
                return [self._dispatch_one(items[0])]
            return list(self._cpu_pool.map(self._dispatch_one, items))
        first = items[0]
        for it in items:
            it["engine"] = "device"  # the vmapped fold IS the device path
        return batched_invoke(
            [it["args"] for it in items], first["max_bins"],
            level_bits=first["key"][-2], max_minv=first["key"][-1])

    def _dispatch(self, args: dict, key: tuple, max_bins: int):
        """Returns ``(outputs, engine)`` — the engine rides the item dict
        (set by whichever thread actually dispatched it, before the
        coalescer hands the result back), so the replay capture is exact
        even for folded/concurrent requests."""
        item = {"args": args, "key": key, "max_bins": max_bins}
        if self._coalescer is None:
            out = self._dispatch_one(item)
        else:
            # bucket = the executable identity: static params + every
            # array's padded shape/dtype — exactly what the compile ledger
            # keys on, so folded requests share one compiled program by
            # construction
            bucket = (
                max_bins, key[-2], key[-1],
                tuple(sorted(
                    (k, np.asarray(v).shape, np.asarray(v).dtype.str)
                    for k, v in args.items()
                )),
            )
            out = self._coalescer.submit(bucket, item)
        return out, item.get("engine", "device")

    def close(self):
        """Release background resources: stop the session sweeper and the
        CPU fan-out pool. Wired into the server's stop() so an in-process
        service (tests, perf) does not leak a waking thread per
        instance."""
        if self._sweeper_stop is not None:
            self._sweeper_stop.set()
        if self._cpu_pool is not None:
            self._cpu_pool.shutdown(wait=False)

    @staticmethod
    def _outputs(out: dict) -> dict:
        return {k: np.asarray(out[k])
                for k in ("assign", "assign_e", "used", "tmpl", "F")}

    def _capture(self, args, key, max_bins, out, engine, tenant=None):
        """Service-boundary replay capture (obs/capsule.py): attached to
        the server's open round trace, tenant-scoped on session solves —
        an anomalous serving round yields a capsule replayable offline
        with the exact tensors this tenant shipped. ``engine`` is the
        per-item engine `_dispatch` threads back (never the shared
        solver's slot — a concurrent tenant's rung must not leak in)."""
        from karpenter_tpu.obs import capsule as _capsule

        if not _capsule.capture_enabled():
            return
        _capsule.record_capture(
            "service.solve", args, self._outputs(out), tenant=tenant,
            engine=engine,
            max_bins=int(max_bins), level_bits=int(key[-2]),
            max_minv=int(key[-1]))

    # -- RPC bodies ------------------------------------------------------

    def solve(self, request: bytes, context) -> bytes:
        import time

        import grpc

        from karpenter_tpu import obs
        from karpenter_tpu.operator.logging import root_cause

        t0 = time.perf_counter()
        outcome = "ok"
        try:
            args, meta = _unpack(request)
            max_bins = int(meta["max_bins"])
            # _invoke reads only the key's tail: (..., max_bins, level_bits,
            # max_minv) — the same layout models/solver.py builds
            key = (max_bins, int(meta.get("level_bits", 20)),
                   int(meta.get("max_minv", 0)))
            # the server half of the cross-boundary trace: a round of its
            # own, linked to the client's reconcile round by trace id
            with obs.round_trace("solver-service", registry=self._registry,
                                 client_trace=meta.get("trace_id") or None):
                out, engine = self._dispatch(args, key, max_bins)
                self._capture(args, key, max_bins, out, engine)
            return _pack(self._outputs(out), {})
        except Exception as e:
            outcome = "error"
            # the client's fallback attributes its rescue to this class:
            # ship the root cause in the status details, not just a string
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{root_cause(e)}: {e}")
        finally:
            self._slo.observe(time.perf_counter() - t0, outcome=outcome,
                              registry=self._registry)

    def register(self, request: bytes, context) -> bytes:
        import grpc

        _, meta = _unpack(request)
        tenant = str(meta.get("tenant") or "")
        try:
            sess = self.sessions.register(tenant, registry=self._registry)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"ValueError: {e}")
        # a client re-registering after a seq-fence break (or evicting a
        # shape family client-side) names the sessions it abandoned:
        # release their bundles from the LRU budget immediately instead of
        # letting orphans squat until the TTL reaper (where they would
        # evict healthy tenants' bundles)
        stale = meta.get("supersedes")
        if stale:
            for sid in [stale] if isinstance(stale, str) else stale:
                self.sessions.release(str(sid), tenant,
                                      registry=self._registry)
        return _pack({}, {
            "session": sess.id,
            "ttl_s": self.sessions.ttl_s,
            "inflight": self.sessions.inflight_budget,
            # codec negotiation: compression is chosen client-side but
            # DECOMPRESSED server-side — the client downgrades to deflate
            # when this server cannot read zstd frames
            "codecs": _server_codecs(),
        })

    def session_solve(self, request: bytes, context) -> bytes:
        import time

        import grpc

        from karpenter_tpu import obs
        from karpenter_tpu.operator.logging import root_cause
        from karpenter_tpu.service import session as sess_mod

        t0 = time.perf_counter()
        outcome = "ok"
        tenant = None
        try:
            arrays, meta = _unpack(request)
            sess = self.sessions.lookup(str(meta.get("session", "")),
                                        registry=self._registry)
            tenant = sess.tenant
            max_bins = int(meta["max_bins"])
            key = (max_bins, int(meta.get("level_bits", 20)),
                   int(meta.get("max_minv", 0)))
            with self.sessions.admit(sess, registry=self._registry):
                args = self.sessions.apply(sess, arrays, meta,
                                           registry=self._registry)
                self.sessions.drain_evictions(registry=self._registry)
                with obs.round_trace(
                    "solver-service", registry=self._registry,
                    client_trace=meta.get("trace_id") or None,
                    tenant=tenant,
                ):
                    # the server half of the session.sync decision ledger:
                    # one tenant-labeled verdict per request, feeding the
                    # /introspect per-tenant rung mix (the client records
                    # its own half in its process). Full-upload reasons
                    # ride the client's `sync_reason` meta, clamped into
                    # the site's closed enum.
                    from karpenter_tpu.obs import decisions

                    if meta.get("mode") == "delta":
                        decisions.record_decision(
                            "session.sync", "delta",
                            registry=self._registry, tenant=tenant)
                    else:
                        decisions.record_decision(
                            "session.sync", "resync",
                            meta.get("sync_reason") or "initial",
                            registry=self._registry, tenant=tenant)
                    out, engine = self._dispatch(args, key, max_bins)
                    self._capture(args, key, max_bins, out, engine,
                                  tenant=tenant)
            return _pack(self._outputs(out), {
                "mode": meta.get("mode", "full"),
                "full_uploads": sess.full_uploads,
                "delta_rounds": sess.delta_rounds,
            })
        except sess_mod.SessionError as e:
            # protocol renegotiation (resync demands) is not a server
            # failure; admission/ordering rejections are — the SLO tracker
            # burns budget for `rejected`/`error` only
            outcome = (
                "resync"
                if isinstance(e, (sess_mod.ResyncRequired,
                                  sess_mod.SessionExpired,
                                  sess_mod.UnknownSession))
                else "rejected"
            )
            context.abort(getattr(grpc.StatusCode, e.status),
                          f"{type(e).__name__}: {e}")
        except Exception as e:
            outcome = "error"
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{root_cause(e)}: {e}")
        finally:
            self._slo.observe(time.perf_counter() - t0, outcome=outcome,
                              registry=self._registry, tenant=tenant)


def serve(port: int = 0, use_native: bool = False, max_workers: int = 4,
          host: str = "127.0.0.1", registry=None):
    """Start the device-plane server; returns (grpc.Server, bound_port).
    Default bind is loopback (tests, local splits); containerized deploys
    pass host="0.0.0.0" so the pod IP is reachable (deploy/operator.yaml).
    `registry` homes the request/SLO families (default: the process
    registry the standalone entrypoint's metrics server exposes).
    KARPENTER_SOLVER_WORKERS overrides the worker pool for multi-tenant
    fleets."""
    from concurrent import futures

    import grpc

    from karpenter_tpu.service.session import env_int

    max_workers = env_int("KARPENTER_SOLVER_WORKERS", max_workers,
                          minimum=1)

    handler = _SolverHandler(use_native=use_native, registry=registry)

    class _Generic(grpc.GenericRpcHandler):
        def service(self, call_details):
            body = {
                _METHOD: handler.solve,
                _METHOD_REGISTER: handler.register,
                _METHOD_SESSION: handler.session_solve,
            }.get(call_details.method)
            if body is not None:
                return grpc.unary_unary_rpc_method_handler(
                    body,
                    request_deserializer=None,  # raw bytes both ways
                    response_serializer=None,
                )
            return None

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), options=_GRPC_OPTS
    )
    server.add_generic_rpc_handlers((_Generic(),))
    # exposed for tests (fault injection on the serving solver) and for
    # embedding callers that want the SLO tracker / session registry
    server.solver_handler = handler
    # stop() must also release the handler's background resources (the
    # session sweeper thread, the CPU fan-out pool): grpc's stop knows
    # nothing about them, so wrap it
    _grpc_stop = server.stop

    def _stop(grace=None):
        handler.close()
        return _grpc_stop(grace)

    server.stop = _stop
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"solver service: failed to bind {host}:{port}")
    server.start()
    return server, bound


from karpenter_tpu.models.solver import TPUSolver  # noqa: E402 (jax stays lazy)

# distinct shape families one client keeps live sessions for: the base
# family plus the doubled bin-axis re-run covers steady state; growth
# families displace the LRU entry (its server session is released on the
# next Register, or TTL-reaped)
_FAMILY_CAP = 4


class _FamilyState:
    """Client-side session state for ONE shape family (every array's
    name/shape/dtype): the server holds one bundle per session, so each
    family the solver dispatches needs its own session to ride deltas."""

    __slots__ = ("session_id", "seq", "sent", "sent_generation", "stale")

    def __init__(self):
        self.session_id: str | None = None
        self.seq = 0
        self.sent: dict | None = None  # last acked args
        self.sent_generation = 0
        self.stale: str | None = None  # abandoned id, released on Register

# transient transport failures worth ONE bounded retry with jittered
# backoff before the in-process rescue: the service restarting or a
# deadline blip is not a dead device plane
_RETRYABLE_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED")


class RemoteSolver(TPUSolver):
    """Drop-in Solver whose kernel dispatch crosses the gRPC boundary:
    tensorize/decode/validation stay host-side, exactly one round trip per
    solve (the in-process `_invoke` seam, served remotely).

    Two dispatch modes. The default stateless mode ships the whole tensor
    snapshot per solve. Passing ``tenant=`` turns on SESSION mode — the
    streaming delta protocol: register once, ship one full snapshot, then
    ship per-round deltas (changed arrays, row-spliced where sparse) with
    the cluster journal window as provenance (``bind_cluster`` wires the
    journal; the Environment does it automatically). The server answers
    protocol drift (gap/opaque/eviction/expiry) with a resync demand and
    the client re-ships a full snapshot exactly once — counted under
    ``karpenter_solver_session_resyncs_total{reason}``.

    Transient transport errors (UNAVAILABLE/DEADLINE_EXCEEDED) get one
    bounded retry with jittered backoff (KARPENTER_SOLVER_RETRY_MS base;
    KARPENTER_SOLVER_RETRY=0 disables) before the in-process rescue; the
    fallback reason then reads ``transport-retryable``, distinguishing a
    flapping service from a server-side solve error (exception class) or
    a hard transport fault (``transport``). Every in-process rescue
    increments `karpenter_solver_remote_fallbacks_total` (labeled by gRPC
    status code + reason) in the injected registry and emits a structured
    warn on the logging plane — a dead device plane shows up on the
    scrape and in grep, not only in throughput."""

    def __init__(self, target: str, registry=None, log=None,
                 tenant: str | None = None):
        import grpc

        from karpenter_tpu.operator import metrics as _metrics
        from karpenter_tpu.operator.logging import make_logger

        super().__init__()
        self._target = target
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._log = (log if log is not None else make_logger()).with_values(
            component="remote_solver", target=target
        )
        self._channel = grpc.insecure_channel(target, options=_GRPC_OPTS)
        self._call = self._channel.unary_unary(
            _METHOD, request_serializer=None, response_deserializer=None
        )
        self._call_register = self._channel.unary_unary(
            _METHOD_REGISTER, request_serializer=None,
            response_deserializer=None
        )
        self._call_session = self._channel.unary_unary(
            _METHOD_SESSION, request_serializer=None,
            response_deserializer=None
        )
        # session-mode state: ONE server session per shape family. A solve
        # can dispatch more than one family (the doubled bin-axis re-run
        # when the bin estimate runs dry), and the server holds exactly one
        # bundle per session — a single shared snapshot slot would make
        # every family flip ship a full upload miscounted as a resync,
        # while per-family sessions pay one full upload per family once
        # and ride deltas thereafter.
        self._tenant = tenant
        self._cluster = None
        self._families: "OrderedDict[tuple, _FamilyState]" = OrderedDict()
        self._released: list = []  # evicted families' ids, freed on Register
        # accounting the perf harness reads back per tenant
        self.session_stats = {
            "full_uploads": 0, "delta_rounds": 0, "resyncs": 0,
            "retries": 0, "bytes_full": 0, "bytes_delta": 0,
        }

    def bind_observability(self, registry=None, log=None):
        """Re-home the fallback counter/log onto an Environment's registry
        and logging plane. The operator builds the solver BEFORE the
        Environment (the solver is a constructor arg), so __main__ binds
        here afterwards — otherwise fallbacks would count in the global
        registry, which serve_metrics never exposes."""
        if registry is not None:
            self._registry = registry
        if log is not None:
            self._log = log.with_values(
                component="remote_solver", target=self._target
            )

    def bind_cluster(self, cluster):
        """Wire the cluster whose delta journal provides the session
        protocol's provenance window (gap/opaque detection rides
        ``Cluster.export_deltas``). Sessionless solvers ignore it."""
        self._cluster = cluster

    # -- transport helpers ----------------------------------------------

    @staticmethod
    def _fallback_reason(e) -> str:
        """Root-cause label for a rescued dispatch: a server-side abort
        carries `ExceptionClass: detail` in the status details (the
        handler's contract); anything else is a transport failure."""
        try:
            details = e.details() or ""
        except Exception:
            details = ""
        head = details.split(":", 1)[0].strip()
        return head if head.isidentifier() else "transport"

    @staticmethod
    def _retryable(e) -> bool:
        try:
            return getattr(e.code(), "name", "") in _RETRYABLE_CODES
        except Exception:
            return False

    @staticmethod
    def _retry_base_s() -> float:
        from karpenter_tpu.service.session import env_float

        return env_float("KARPENTER_SOLVER_RETRY_MS", 50.0,
                         minimum=0.0) / 1000.0

    def _call_with_retry(self, call, payload: bytes) -> bytes:
        import grpc

        from karpenter_tpu.operator import metrics as _metrics

        try:
            return call(payload)
        except grpc.RpcError as e:
            from karpenter_tpu.service.session import env_bool

            if (not env_bool("KARPENTER_SOLVER_RETRY", True)
                    or not self._retryable(e)):
                raise
            import random
            import time as _time

            delay = self._retry_base_s() * (0.5 + random.random())
            _time.sleep(delay)
            self.session_stats["retries"] += 1
            try:
                code = str(e.code())
            except Exception:
                code = "UNKNOWN"
            self._registry.counter(
                _metrics.SOLVER_REMOTE_RETRIES,
                "transient-transport retries before the in-process rescue",
            ).inc(code=code)
            self._log.warn("transient solver-service error; retrying once",
                           code=code, delay_ms=round(delay * 1000.0, 1))
            return call(payload)

    def _fallback(self, e, args, key, max_bins):
        """Solve in-process rather than failing the provisioning round
        (the Solver seam's fallback stance — same philosophy as the
        engine ladder in bench.py), attributing the rescue to its root
        cause: server exception class, retried-and-still-down transport
        (`transport-retryable`), or hard transport."""
        from karpenter_tpu import obs
        from karpenter_tpu.operator import metrics as _metrics

        try:
            code = str(e.code())
        except Exception:
            code = "UNKNOWN"
        reason = self._fallback_reason(e)
        if reason == "transport" and self._retryable(e):
            reason = "transport-retryable"
        # bounded label cardinality (the decision-ledger stance): a server
        # exception class outside the known set clamps to "server-error"
        # instead of minting a fresh series per novel bug
        from karpenter_tpu.obs import decisions

        if reason not in decisions.SOLVER_FALLBACK_REASONS:
            reason = "server-error"
        trace_id = obs.current_trace_id()
        self._registry.counter(
            _metrics.SOLVER_REMOTE_FALLBACKS,
            "RemoteSolver dispatches rescued by the in-process kernel",
        ).inc(code=code, reason=reason)
        self._log.warn("solver service unavailable; solving in-process",
                       code=code, reason=reason, trace=trace_id or "")
        out = super()._invoke(args, key, max_bins)
        if self._route is not None:
            # the solve's solver.route verdict keeps the in-process rung
            # the rescue actually ran, but the REASON says why it left the
            # service rung — the downgrade is visible on the ledger
            self._route = (self._route[0], "remote-fallback")
        return out

    def _record_payload(self, kind: str, nbytes: int, codec: str | None):
        from karpenter_tpu.operator import metrics as _metrics

        self.session_stats[f"bytes_{kind}"] = (
            self.session_stats.get(f"bytes_{kind}", 0) + nbytes)
        self._registry.histogram(
            _metrics.SOLVER_REQUEST_BYTES,
            "wire payload sizes by kind and codec",
            buckets=_metrics.SOLVER_REQUEST_BYTES_BUCKETS,
        ).observe(nbytes, kind=kind, codec=codec or "none")

    # -- dispatch --------------------------------------------------------

    def _invoke(self, args, key, max_bins):
        import grpc

        from karpenter_tpu import obs

        trace_id = obs.current_trace_id()
        meta = {"max_bins": int(max_bins), "level_bits": int(key[-2]),
                "max_minv": int(key[-1]), "trace_id": trace_id or ""}
        try:
            if self._tenant is None:
                codec = _env_codec()
                payload = _pack(dict(args), meta, codec=codec)
                self._record_payload("full", len(payload), codec)
                blob = self._call_with_retry(self._call, payload)
            else:
                blob = self._session_round(args, meta)
        except grpc.RpcError as e:
            return self._fallback(e, args, key, max_bins)
        self._last_engine = "remote"
        self._route = ("service", "ok")
        arrays, _ = _unpack(blob)
        arrays["used"] = arrays["used"].astype(bool)
        arrays["F"] = arrays["F"].astype(bool)
        return arrays

    # -- session mode ----------------------------------------------------

    def _count_resync(self, reason: str):
        from karpenter_tpu.obs import decisions
        from karpenter_tpu.operator import metrics as _metrics

        self.session_stats["resyncs"] += 1
        self._registry.counter(
            _metrics.SOLVER_SESSION_RESYNCS,
            "session full re-uploads by cause (journal gaps, opaque "
            "deltas, server resync demands)",
        ).inc(
            # the label universe IS the session.sync decision enum: a new
            # server error class can never mint an unbounded series here
            # while the ledger stays closed (obs/decisions.py)
            reason=decisions.canonical_reason("session.sync", reason))

    def _register_session(self, st: _FamilyState):
        req: dict = {"tenant": self._tenant}
        stale = list(self._released)
        if st.stale is not None:
            stale.append(st.stale)
        if stale:
            req["supersedes"] = stale
        blob = self._call_with_retry(
            self._call_register, _pack({}, req))
        _, meta = _unpack(blob)
        st.stale = None
        self._released.clear()
        st.session_id = meta["session"]
        self._server_codecs = set(meta.get("codecs") or ["deflate"])
        st.seq = 0
        st.sent = None
        st.sent_generation = 0

    def _upload_codec(self) -> str | None:
        """The configured codec, downgraded to what the server can read
        (the Register handshake's `codecs`)."""
        codec = _env_codec()
        if codec == "zstd" and "zstd" not in getattr(
                self, "_server_codecs", {"deflate", "zstd"}):
            return "deflate"
        return codec

    # -- per-family session state (tests read the properties) ------------

    def _family_state(self, args) -> _FamilyState:
        """The session state for this dispatch's shape family, created on
        first sight; the LRU family beyond the cap is evicted and its
        server session queued for release on the next Register."""
        key = tuple(sorted(
            (k, v.shape, str(v.dtype)) for k, v in args.items()))
        st = self._families.pop(key, None)
        if st is None:
            st = _FamilyState()
            while len(self._families) >= _FAMILY_CAP:
                _, old = self._families.popitem(last=False)
                if old.session_id is not None:
                    self._released.append(old.session_id)
        self._families[key] = st  # most-recently-used at the end
        return st

    @property
    def _session_id(self):
        st = next(reversed(self._families.values()), None)
        return st.session_id if st is not None else None

    @property
    def _session_seq(self):
        st = next(reversed(self._families.values()), None)
        return st.seq if st is not None else 0

    def _session_round(self, args, meta_base: dict) -> bytes:
        """One solve over the session protocol: build the smallest payload
        the session state allows (delta when the server holds our last
        snapshot, full otherwise), and answer exactly ONE server resync
        demand with a full re-upload before giving up to the caller's
        fallback."""
        import grpc

        from karpenter_tpu.obs import decisions

        args = {k: np.asarray(v) for k, v in args.items()}
        st = self._family_state(args)
        payload, pending = self._session_payload(args, meta_base, st)
        try:
            blob = self._call_with_retry(self._call_session, payload)
        except grpc.RpcError as e:
            head = self._fallback_reason(e)
            if head not in ("ResyncRequired", "SessionExpired",
                            "UnknownSession", "OutOfOrderDelta"):
                raise
            self._count_resync(head)
            if head != "ResyncRequired":
                # expiry/unknown: re-register. Out-of-order: the server's
                # seq fence is ahead of ours (a retry that actually landed)
                # — a fresh session is cheaper than guessing its fence.
                # The abandoned session may still be LIVE server-side
                # (out-of-order keeps it); name it in the next Register so
                # its multi-MB bundle leaves the shared LRU budget NOW,
                # not a TTL later (orphans would evict healthy tenants).
                st.stale = st.session_id
                st.session_id = None
            st.sent = None  # the server's view is gone either way
            payload, pending = self._session_payload(args, meta_base, st,
                                                     demand_reason=head)
            blob = self._call_with_retry(self._call_session, payload)
        decision = pending.pop("decision")
        self._commit_session(st, **pending)
        # the round's ONE client-side session.sync verdict: the rung the
        # round ultimately shipped (a demand-answered round records the
        # resync rung with the server's demand class as the reason)
        decisions.record_decision("session.sync", *decision,
                                  registry=self._registry,
                                  tenant=self._tenant)
        return blob

    def _session_payload(self, args, meta_base: dict, st: _FamilyState,
                         demand_reason: str | None = None) -> tuple:
        """(wire payload, commit kwargs). Decides full vs delta: full on
        first contact with this shape family, a journal gap, or an opaque
        journal entry; delta otherwise — changed arrays only, row-spliced
        when less than half the leading axis moved. `args` shapes always
        match `st.sent` by construction (the family key IS every array's
        name/shape/dtype), so there is no shape-change case.
        `demand_reason` names the server demand a re-upload answers (the
        session.sync decision's reason, also shipped as `sync_reason`
        meta so the server's ledger half attributes the full upload)."""
        from karpenter_tpu.service.session import ROWS_SUFFIX, VALS_SUFFIX

        if st.session_id is None:
            self._register_session(st)
        seq = st.seq + 1
        meta = dict(meta_base)
        meta.update(session=st.session_id, seq=seq,
                    tenant=self._tenant)
        journal = None
        generation = seq
        if self._cluster is not None:
            journal, generation = self._cluster.export_deltas(
                st.sent_generation)
        full_reason = None
        if st.sent is None:
            full_reason = ""  # initial upload: not a resync
        elif self._cluster is not None and journal is None:
            full_reason = "journal-gap"
        elif journal is not None and any(e is None for e in journal):
            full_reason = "opaque-delta"
        if full_reason is not None:
            if full_reason:
                self._count_resync(full_reason)
            sync_reason = demand_reason or full_reason or "initial"
            meta.update(mode="full", generation=generation,
                        sync_reason=sync_reason)
            codec = self._upload_codec()
            payload = _pack(args, meta, codec=codec)
            self._record_payload("full", len(payload), codec)
            stat = "full_uploads"
            decision = ("resync", sync_reason)
        else:
            patch: dict = {}
            wire: dict = {}
            for k, v in args.items():
                old = st.sent[k]
                if v.ndim >= 1 and v.shape[0] > 8:
                    # ONE elementwise pass serves both questions (changed
                    # at all? which rows?) — these are the multi-MB
                    # arrays, every reconcile round
                    moved = np.flatnonzero(
                        (old != v).reshape(v.shape[0], -1).any(axis=1))
                    if moved.size == 0:
                        continue
                    if moved.size <= v.shape[0] // 2:
                        patch[k] = "rows"
                        wire[k + ROWS_SUFFIX] = moved.astype(np.int64)
                        wire[k + VALS_SUFFIX] = v[moved]
                        continue
                elif np.array_equal(old, v):
                    continue
                patch[k] = "full"
                wire[k] = v
            meta.update(mode="delta", base_seq=st.seq,
                        patch=patch, journal=journal,
                        generation=generation)
            payload = _pack(wire, meta)  # deltas are small: no codec
            self._record_payload("delta", len(payload), None)
            stat = "delta_rounds"
            decision = ("delta", "ok")
        return payload, dict(args=args, seq=seq, generation=generation,
                             stat=stat, decision=decision)

    def _commit_session(self, st: _FamilyState, args, seq, generation, stat):
        st.sent = args
        st.seq = seq
        st.sent_generation = generation
        self.session_stats[stat] += 1


def main(argv=None) -> int:
    """`python -m karpenter_tpu.service.solver_service [--port N] [--native]`
    — run the device plane standalone (the gRPC analog of kwok/main.go for
    the solver half of the two-plane split)."""
    import argparse
    import signal
    import threading

    ap = argparse.ArgumentParser(prog="karpenter_tpu.service.solver_service")
    ap.add_argument("--port", type=int, default=8400)
    ap.add_argument("--host", default="0.0.0.0",
                    help="bind address (containers need the pod IP "
                         "reachable; use 127.0.0.1 for local-only)")
    ap.add_argument("--native", action="store_true",
                    help="serve the C++ engine instead of the accelerator")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics + /healthz + /slo for this device "
                         "plane (0 = off); bind narrows via "
                         "KARPENTER_METRICS_BIND like the operator's")
    args = ap.parse_args(argv)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests)
    server, bound = serve(port=args.port, use_native=args.native, host=args.host)
    metrics_server = None
    if args.metrics_port:
        from karpenter_tpu.__main__ import serve_metrics
        from karpenter_tpu.operator import metrics as _metrics

        metrics_server = serve_metrics(
            _metrics.REGISTRY, args.metrics_port,
            host=env_str("KARPENTER_METRICS_BIND", ""),
        )
        print(f"solver service: metrics on :{args.metrics_port} "
              f"(/metrics /healthz /slo /introspect)", flush=True)
    print(f"solver service: listening on {args.host}:{bound} "
          f"({'native' if args.native else 'device'} engine)", flush=True)
    stop.wait()
    if metrics_server is not None:
        metrics_server.shutdown()
    server.stop(grace=2.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
