"""gRPC solver service: the device plane as a standalone process.

Wire contract (raw-bytes unary RPC, no generated stubs — the method is
`/karpenter.Solver/Solve`):

- request: an .npz archive of the kernel's tensor snapshot (the exact args
  dict `TPUSolver._invoke` builds) plus a `__meta__` JSON entry carrying
  the static solve parameters (max_bins, level_bits, max_minv).
- response: an .npz archive of the kernel outputs
  (assign/assign_e/used/tmpl/F).

The server executes on whatever backend its process sees — the tunneled
TPU in production (`python -m karpenter_tpu.service.solver_service`), CPU
or the C++ engine elsewhere — while the client process needs no jax at
dispatch time. The latency budget for the hop rides inside the solve
target the same way the tunnel round trip does (BASELINE.md <200 ms
includes it).

Cross-boundary SLO tracing (deploy/README.md "Device-plane & SLO
telemetry"): the client threads its open round's trace id through the
`__meta__` payload (`trace_id`), and the server opens one linked
round trace per request (`solver-service`, `client_trace=<id>`) so a
grep for the client's trace id finds both halves of the hop. Request
durations feed `karpenter_solver_request_seconds{outcome}` plus the
rolling-quantile/error-budget SLO tracker (obs/devplane.py) that the
metrics server's `/slo` endpoint snapshots; a server-side solve failure
aborts the RPC with the root-cause exception class in the status
details, which the client surfaces as the `reason` label on
`karpenter_solver_remote_fallbacks_total` and in its structured warning.
"""

from __future__ import annotations

import io
import json

import numpy as np

_METHOD = "/karpenter.Solver/Solve"
_MAX_MSG = 256 * 1024 * 1024  # the 50k snapshot is ~tens of MB uncompressed
_GRPC_OPTS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
]


def _pack(arrays: dict, meta: dict) -> bytes:
    buf = io.BytesIO()
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    np.savez(buf, **payload)
    return buf.getvalue()


def _unpack(blob: bytes) -> tuple:
    with np.load(io.BytesIO(blob)) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode()) if "__meta__" in z.files else {}
    return arrays, meta


def _env_latency_slo() -> float | None:
    """KARPENTER_SOLVER_SLO_MS: per-request latency objective in ms
    (unset = error-only SLO)."""
    import os

    v = os.environ.get("KARPENTER_SOLVER_SLO_MS", "").strip()
    if not v:
        return None
    try:
        return float(v) / 1000.0
    except ValueError:
        return None


class _SolverHandler:
    """Server-side execution through the solver's own `_invoke` stack: the
    shared jitted packed kernel (one compile per shape bucket, one
    device→host pull) and the calibrated small-batch native routing both
    apply on the serving side exactly as in-process. Every request runs as
    one linked round trace and lands in the service SLO tracker."""

    def __init__(self, use_native: bool = False, registry=None):
        from karpenter_tpu.models.solver import NativeSolver, TPUSolver
        from karpenter_tpu.obs import devplane
        from karpenter_tpu.operator import metrics as _metrics

        self._solver = NativeSolver() if use_native else TPUSolver()
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._slo = devplane.slo_tracker(
            "solver_service", latency_slo=_env_latency_slo()
        )

    def solve(self, request: bytes, context) -> bytes:
        import time

        import grpc

        from karpenter_tpu import obs
        from karpenter_tpu.operator.logging import root_cause

        t0 = time.perf_counter()
        outcome = "ok"
        try:
            args, meta = _unpack(request)
            max_bins = int(meta["max_bins"])
            # _invoke reads only the key's tail: (..., max_bins, level_bits,
            # max_minv) — the same layout models/solver.py builds
            key = (max_bins, int(meta.get("level_bits", 20)),
                   int(meta.get("max_minv", 0)))
            # the server half of the cross-boundary trace: a round of its
            # own, linked to the client's reconcile round by trace id
            with obs.round_trace("solver-service", registry=self._registry,
                                 client_trace=meta.get("trace_id") or None):
                out = self._solver._invoke(args, key, max_bins)
            return _pack(
                {k: np.asarray(out[k]) for k in ("assign", "assign_e", "used", "tmpl", "F")},
                {},
            )
        except Exception as e:
            outcome = "error"
            # the client's fallback attributes its rescue to this class:
            # ship the root cause in the status details, not just a string
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{root_cause(e)}: {e}")
        finally:
            self._slo.observe(time.perf_counter() - t0, outcome=outcome,
                              registry=self._registry)


def serve(port: int = 0, use_native: bool = False, max_workers: int = 4,
          host: str = "127.0.0.1", registry=None):
    """Start the device-plane server; returns (grpc.Server, bound_port).
    Default bind is loopback (tests, local splits); containerized deploys
    pass host="0.0.0.0" so the pod IP is reachable (deploy/operator.yaml).
    `registry` homes the request/SLO families (default: the process
    registry the standalone entrypoint's metrics server exposes)."""
    from concurrent import futures

    import grpc

    handler = _SolverHandler(use_native=use_native, registry=registry)

    class _Generic(grpc.GenericRpcHandler):
        def service(self, call_details):
            if call_details.method == _METHOD:
                return grpc.unary_unary_rpc_method_handler(
                    handler.solve,
                    request_deserializer=None,  # raw bytes both ways
                    response_serializer=None,
                )
            return None

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), options=_GRPC_OPTS
    )
    server.add_generic_rpc_handlers((_Generic(),))
    # exposed for tests (fault injection on the serving solver) and for
    # embedding callers that want the SLO tracker
    server.solver_handler = handler
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"solver service: failed to bind {host}:{port}")
    server.start()
    return server, bound


from karpenter_tpu.models.solver import TPUSolver  # noqa: E402 (jax stays lazy)


class RemoteSolver(TPUSolver):
    """Drop-in Solver whose kernel dispatch crosses the gRPC boundary:
    tensorize/decode/validation stay host-side, exactly one round trip per
    solve (the in-process `_invoke` seam, served remotely).

    Fallbacks are an operational signal, not just a log line: every
    in-process rescue increments `karpenter_solver_remote_fallbacks_total`
    (labeled by gRPC status code) in the injected registry and emits a
    structured warn on the logging plane — a dead device plane shows up on
    the scrape and in grep, not only in throughput."""

    def __init__(self, target: str, registry=None, log=None):
        import grpc

        from karpenter_tpu.operator import metrics as _metrics
        from karpenter_tpu.operator.logging import make_logger

        super().__init__()
        self._target = target
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._log = (log if log is not None else make_logger()).with_values(
            component="remote_solver", target=target
        )
        self._channel = grpc.insecure_channel(target, options=_GRPC_OPTS)
        self._call = self._channel.unary_unary(
            _METHOD, request_serializer=None, response_deserializer=None
        )

    def bind_observability(self, registry=None, log=None):
        """Re-home the fallback counter/log onto an Environment's registry
        and logging plane. The operator builds the solver BEFORE the
        Environment (the solver is a constructor arg), so __main__ binds
        here afterwards — otherwise fallbacks would count in the global
        registry, which serve_metrics never exposes."""
        if registry is not None:
            self._registry = registry
        if log is not None:
            self._log = log.with_values(
                component="remote_solver", target=self._target
            )

    @staticmethod
    def _fallback_reason(e) -> str:
        """Root-cause label for a rescued dispatch: a server-side abort
        carries `ExceptionClass: detail` in the status details (the
        handler's contract); anything else is a transport failure."""
        try:
            details = e.details() or ""
        except Exception:
            details = ""
        head = details.split(":", 1)[0].strip()
        return head if head.isidentifier() else "transport"

    def _invoke(self, args, key, max_bins):
        import grpc

        from karpenter_tpu import obs
        from karpenter_tpu.operator import metrics as _metrics

        # the round's trace id rides the request meta so the server can
        # open a LINKED round trace: one grep joins both halves of the hop
        trace_id = obs.current_trace_id()
        meta = {"max_bins": int(max_bins), "level_bits": int(key[-2]),
                "max_minv": int(key[-1]), "trace_id": trace_id or ""}
        try:
            blob = self._call(_pack(dict(args), meta))
        except grpc.RpcError as e:
            # device plane unreachable or server solve failed: solve
            # in-process rather than failing the provisioning round (the
            # Solver seam's fallback stance — same philosophy as the
            # engine ladder in bench.py), attributing the rescue to its
            # root cause (server exception class, or transport)
            try:
                code = str(e.code())
            except Exception:
                code = "UNKNOWN"
            reason = self._fallback_reason(e)
            self._registry.counter(
                _metrics.SOLVER_REMOTE_FALLBACKS,
                "RemoteSolver dispatches rescued by the in-process kernel",
            ).inc(code=code, reason=reason)
            self._log.warn("solver service unavailable; solving in-process",
                           code=code, reason=reason, trace=trace_id or "")
            return super()._invoke(args, key, max_bins)
        self._last_engine = "remote"
        arrays, _ = _unpack(blob)
        arrays["used"] = arrays["used"].astype(bool)
        arrays["F"] = arrays["F"].astype(bool)
        return arrays


def main(argv=None) -> int:
    """`python -m karpenter_tpu.service.solver_service [--port N] [--native]`
    — run the device plane standalone (the gRPC analog of kwok/main.go for
    the solver half of the two-plane split)."""
    import argparse
    import signal
    import threading

    ap = argparse.ArgumentParser(prog="karpenter_tpu.service.solver_service")
    ap.add_argument("--port", type=int, default=8400)
    ap.add_argument("--host", default="0.0.0.0",
                    help="bind address (containers need the pod IP "
                         "reachable; use 127.0.0.1 for local-only)")
    ap.add_argument("--native", action="store_true",
                    help="serve the C++ engine instead of the accelerator")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics + /healthz + /slo for this device "
                         "plane (0 = off); bind narrows via "
                         "KARPENTER_METRICS_BIND like the operator's")
    args = ap.parse_args(argv)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests)
    server, bound = serve(port=args.port, use_native=args.native, host=args.host)
    metrics_server = None
    if args.metrics_port:
        import os

        from karpenter_tpu.__main__ import serve_metrics
        from karpenter_tpu.operator import metrics as _metrics

        metrics_server = serve_metrics(
            _metrics.REGISTRY, args.metrics_port,
            host=os.environ.get("KARPENTER_METRICS_BIND", ""),
        )
        print(f"solver service: metrics on :{args.metrics_port} "
              f"(/metrics /healthz /slo)", flush=True)
    print(f"solver service: listening on {args.host}:{bound} "
          f"({'native' if args.native else 'device'} engine)", flush=True)
    stop.wait()
    if metrics_server is not None:
        metrics_server.shutdown()
    server.stop(grace=2.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
