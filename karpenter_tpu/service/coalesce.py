"""Request coalescing for the solver fleet service.

CvxCluster's amortization claim (PAPERS.md, arxiv 2605.01614) only
materializes as a *service* if concurrent tenants' solves actually share
compile families and ride batched dispatches. This module is that fold: a
short-window batcher keyed on the pow-2 shape bucket plus the static solve
params — exactly the executable identity the compile ledger keys on — so
solves that would compile and dispatch the SAME program instead stack on a
batch axis and ride ONE vmapped device call
(:func:`karpenter_tpu.models.solver.batched_invoke`), demuxed per tenant
on return.

Mechanics: the first request of a bucket becomes the *leader*, sleeps the
coalescing window (``KARPENTER_COALESCE_WINDOW_MS``), then dispatches every
request that joined; followers block on the bucket's event. A bucket that
reaches ``KARPENTER_COALESCE_MAX`` closes so later arrivals start a fresh
one (its leader runs its own window). A single-member bucket dispatches
through the ordinary per-request path — native routing and all — so
coalescing can only ever ADD batch-mates, never change a lone solve's
engine. Batch shape lands on
``karpenter_solver_coalesce_batch_size``; requests that shared a dispatch
count on ``karpenter_solver_coalesced_requests_total``.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["Coalescer", "coalesce_window_s"]


def coalesce_window_s() -> float:
    """KARPENTER_COALESCE_WINDOW_MS: the fold window in ms (0 disables
    coalescing entirely — every request dispatches alone)."""
    from karpenter_tpu.service.session import env_float

    return env_float("KARPENTER_COALESCE_WINDOW_MS", 0.0,
                     minimum=0.0) / 1000.0


def _env_max_batch() -> int:
    from karpenter_tpu.service.session import env_int

    return env_int("KARPENTER_COALESCE_MAX", 8, minimum=1)


class _Bucket:
    __slots__ = ("items", "results", "error", "done", "closed")

    def __init__(self):
        self.items: list = []
        self.results = None
        self.error = None
        self.done = threading.Event()
        self.closed = False


class Coalescer:
    """Fold same-bucket concurrent solves into one dispatch.

    ``dispatch_one(args)`` runs a lone solve through the ordinary path;
    ``dispatch_many(args_list)`` runs a stacked batch and returns one
    result per input, order-preserving."""

    def __init__(self, dispatch_one, dispatch_many, window_s: float,
                 max_batch: int | None = None, registry=None):
        self._dispatch_one = dispatch_one
        self._dispatch_many = dispatch_many
        self.window_s = window_s
        self.max_batch = max_batch if max_batch is not None else _env_max_batch()
        self._registry = registry
        self._lock = threading.Lock()
        self._buckets: dict = {}  # bucket key -> open _Bucket

    def submit(self, key, args):
        """Solve ``args`` inside the ``key`` bucket; blocks until the
        bucket's dispatch returns and yields this request's result."""
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None or bucket.closed:
                bucket = _Bucket()
                self._buckets[key] = bucket
                leader = True
            else:
                leader = False
            idx = len(bucket.items)
            bucket.items.append(args)
            if len(bucket.items) >= self.max_batch:
                bucket.closed = True
                if self._buckets.get(key) is bucket:
                    del self._buckets[key]
        if leader:
            self._lead(key, bucket)
            bucket.done.wait()
        else:
            # spans are thread-local, so the batch's solve.kernel leaf
            # lands only in the LEADER's round trace; followers open a
            # device-kind wait leaf in their OWN linked round so a grep
            # by their client's trace id still finds where the request's
            # device time went (and to which batch it folded)
            from karpenter_tpu import obs

            with obs.span("solve.coalesce_wait", kind="device") as sp:
                bucket.done.wait()
                if sp is not None:
                    if sp.attrs is None:
                        sp.attrs = {}
                    sp.attrs["batch"] = len(bucket.items)
        if bucket.error is not None:
            raise bucket.error
        return bucket.results[idx]

    def _lead(self, key, bucket: _Bucket):
        # the window is the fold opportunity: followers join while the
        # leader sleeps. A full bucket already closed itself; the sleep
        # still runs (bounded, a few ms) — simplicity over the last ms.
        if self.window_s > 0:
            time.sleep(self.window_s)
        with self._lock:
            bucket.closed = True
            if self._buckets.get(key) is bucket:
                del self._buckets[key]
            items = list(bucket.items)
        try:
            self._observe(len(items))
            if len(items) == 1:
                bucket.results = [self._dispatch_one(items[0])]
            else:
                bucket.results = self._dispatch_many(items)
        except Exception as e:  # propagated to every member
            bucket.error = e
        finally:
            bucket.done.set()

    def _observe(self, n: int):
        if self._registry is None:
            return
        from karpenter_tpu.operator import metrics as m

        self._registry.histogram(
            m.SOLVER_COALESCE_BATCH,
            "requests folded per coalesced dispatch window",
            buckets=m.SOLVER_COALESCE_BUCKETS,
        ).observe(n)
        if n > 1:
            self._registry.counter(
                m.SOLVER_COALESCED,
                "requests that shared a coalesced device dispatch",
            ).inc(n)
