"""The two-plane boundary: a gRPC Solver service (SURVEY.md §2.11/§7).

The reference isolates the outside world behind the CloudProvider SPI; our
build adds one more seam in the same spirit — the HOST plane (controllers,
store, state) and the DEVICE plane (the accelerator kernel) may live in
different processes. `serve()` runs the device plane as a gRPC server;
`RemoteSolver` is a drop-in `Solver` whose kernel dispatch crosses the
wire. Everything else — tensorize, decode, validation, the host fallback —
stays host-side, so the payload is exactly the kernel's tensor snapshot
and the reply is its packed outputs (the same seam `TPUSolver._invoke`
already is in-process).

Since ISSUE 7 the service is multi-tenant: `RemoteSolver(..., tenant=)`
speaks the streaming delta protocol against per-tenant server-side
snapshot caches (session.py), concurrent same-shape solves coalesce into
one device dispatch (coalesce.py), and per-tenant budgets/SLO surfaces
ride the PR-6 telemetry plane — deploy/README.md "Multi-tenant solver
service" documents the wire format and knobs.
"""

from karpenter_tpu.service.coalesce import Coalescer
from karpenter_tpu.service.session import SessionRegistry, TenantSession
from karpenter_tpu.service.solver_service import RemoteSolver, serve

__all__ = [
    "Coalescer",
    "RemoteSolver",
    "SessionRegistry",
    "TenantSession",
    "serve",
]
