"""Per-tenant sessions for the multi-tenant solver fleet service.

The streaming delta protocol's server half (deploy/README.md "Multi-tenant
solver service"): a tenant registers a session, ships ONE full tensor
snapshot, then ships per-round deltas — changed arrays, plus row-splices
for arrays whose leading axis moved sparsely — and the server maintains
the solve-ready bundle per session. Patching reuses the SAME in-place row
semantics the in-process disruption snapshot uses
(:func:`karpenter_tpu.ops.tensorize.splice_rows`, the primitive
``ExistingSnapshot.apply_delta`` splices dirty existing-node rows with),
so a delta-advanced server bundle is bit-identical to a full upload by
construction — the parity suite in tests/test_multitenant_service.py pins
it.

Protocol invariants enforced here, each with its own exception class (the
gRPC layer maps the class name into the status details, which the client's
fallback/resync logic and the ``reason`` metric label key on):

- **OutOfOrderDelta** — a request's ``seq`` must strictly increase per
  session; replays and reordered retries are rejected, never applied.
- **ResyncRequired** — a delta whose ``base_seq`` does not match the
  session's last applied seq (journal gap), whose journal window carries
  an opaque (null) entry, whose patch shapes mismatch the cached family,
  or that arrives after the session's bundle was evicted. The client
  answers with one full re-upload.
- **SessionExpired / UnknownSession** — the TTL reaper dropped the
  session (or it never existed); the client re-registers and re-ships a
  full snapshot.
- **TenantBudgetExceeded** — admission control: each tenant holds at most
  ``KARPENTER_TENANT_INFLIGHT`` requests in flight; excess is rejected as
  backpressure instead of queueing without bound.
- **CrossTenantBleed** — the isolation assertion hook: every cached
  bundle is tagged with its owner tenant and every patch re-checks the
  tag. A mismatch aborts the request, fires the ``cross-tenant-bleed``
  anomaly (the flight recorder dumps the round), and lands on
  ``karpenter_solver_bleed_checks_total{outcome="bleed"}`` — the scrape
  must never show a bleed check silently passing over corrupt state.

Cache economics: bundles live under one LRU byte budget
(``KARPENTER_SESSION_CACHE_BYTES``) across all sessions; eviction drops
the least-recently-used OTHER session's bundle (never the one being
written) and is visible on
``karpenter_solver_session_cache_evictions_total`` plus the
``karpenter_solver_session_cache_bytes`` gauge — a fleet whose tenants
thrash each other's snapshots shows up on the scrape, not as mystery
resyncs.

Expiry is enforced twice: reap-on-access (``lookup``/``register``) and a
periodic **sweep** (:meth:`SessionRegistry.sweep`, run by the service's
sweeper thread every ``KARPENTER_SESSION_SWEEP_S``) that reaps idle
expired sessions and releases their bundle bytes without any client
touching the server — counted on
``karpenter_solver_session_sweeps_total``.
"""

from __future__ import annotations

import threading
import time
import uuid
from contextlib import contextmanager

import numpy as np

from karpenter_tpu.ops.tensorize import splice_rows

__all__ = [
    "SessionRegistry",
    "TenantSession",
    "SessionError",
    "UnknownSession",
    "SessionExpired",
    "ResyncRequired",
    "OutOfOrderDelta",
    "TenantBudgetExceeded",
    "CrossTenantBleed",
    "ROWS_SUFFIX",
    "VALS_SUFFIX",
    "env_int",
    "env_float",
    "env_bool",
]

# wire names of a row-spliced delta entry: "<key>//rows" carries the row
# indices, "<key>//vals" the replacement rows ("//" cannot appear in a
# kernel-arg name)
ROWS_SUFFIX = "//rows"
VALS_SUFFIX = "//vals"


class SessionError(Exception):
    """Base of every protocol rejection; ``status`` names the gRPC code
    the service maps it to (resolved there — this module stays
    grpc-free)."""

    status = "FAILED_PRECONDITION"


class UnknownSession(SessionError):
    status = "FAILED_PRECONDITION"


class SessionExpired(SessionError):
    status = "FAILED_PRECONDITION"


class ResyncRequired(SessionError):
    status = "FAILED_PRECONDITION"


class OutOfOrderDelta(SessionError):
    status = "INVALID_ARGUMENT"


class TenantBudgetExceeded(SessionError):
    status = "RESOURCE_EXHAUSTED"


class CrossTenantBleed(SessionError):
    status = "INTERNAL"


# the ONE env-knob parser trio, hoisted to utils/envknobs.py when the
# decision ledger needed the same semantics below the service layer;
# re-exported here so every existing importer (coalesce, solver_service,
# perf, bench) keeps its spelling
from karpenter_tpu.utils.envknobs import env_bool, env_float, env_int  # noqa: E402,F401


class TenantSession:
    """One tenant's registered stream: seq fencing state plus the cached
    solve bundle. Mutated only under the owning registry's lock."""

    def __init__(self, session_id: str, tenant: str, now: float):
        self.id = session_id
        self.tenant = tenant
        self.created = now
        self.last_used = now
        self.last_seq = 0  # highest applied request seq (0 = nothing yet)
        self.bundle: dict | None = None  # solve-ready kernel args
        self.bundle_tenant: str | None = None  # isolation tag
        self.bundle_bytes = 0
        # accounting the perf row reads back through response meta
        self.full_uploads = 0
        self.delta_rounds = 0


def _nbytes(arrays: dict) -> int:
    return int(sum(np.asarray(v).nbytes for v in arrays.values()))


class SessionRegistry:
    """All live tenant sessions of one server, plus the shared LRU byte
    budget and the per-tenant admission budget."""

    def __init__(self, byte_budget: int | None = None,
                 ttl_s: float | None = None,
                 inflight_budget: int | None = None,
                 now=time.monotonic):
        self.byte_budget = (
            byte_budget if byte_budget is not None
            else env_int("KARPENTER_SESSION_CACHE_BYTES", 1 << 30)
        )
        self.ttl_s = (
            ttl_s if ttl_s is not None
            else env_float("KARPENTER_SESSION_TTL_S", 900.0)
        )
        self.inflight_budget = (
            inflight_budget if inflight_budget is not None
            else env_int("KARPENTER_TENANT_INFLIGHT", 4)
        )
        # hard cap on live sessions: tenant ids and Register calls are
        # client-supplied, so a flapping client re-registering per solve
        # must not grow _sessions unbounded for a full TTL (the same
        # bounded-memory stance as the SloTracker tenant cap and the
        # in-flight pop-on-drain); past the cap the LRU session is
        # dropped and its owner resyncs
        self.session_cap = env_int("KARPENTER_SESSION_MAX", 4096,
                                   minimum=1)
        self._now = now
        self._lock = threading.Lock()
        self._sessions: dict = {}  # session id -> TenantSession
        self._inflight: dict = {}  # tenant -> in-flight request count
        self._total_bytes = 0
        self._evictions_pending: list = []  # tenants evicted by last store

    # -- lifecycle -------------------------------------------------------

    def register(self, tenant: str, registry=None) -> TenantSession:
        if not tenant:
            raise ValueError("tenant id must be non-empty")
        now = self._now()
        sess = TenantSession(f"s-{uuid.uuid4().hex[:16]}", tenant, now)
        with self._lock:
            self._reap(now)
            while len(self._sessions) >= self.session_cap:
                lru = min(self._sessions.values(),
                          key=lambda s: s.last_used)
                self._drop(lru)
            self._sessions[sess.id] = sess
            count = len(self._sessions)
        self._metric_gauge(registry, count)
        return sess

    def release(self, session_id: str, tenant: str, registry=None) -> bool:
        """Drop an abandoned session NOW (the Register `supersedes` path)
        instead of letting its bundle squat in the LRU byte budget until
        the TTL reaper. Tenant-checked: a client can only release its own
        sessions. Unknown/already-reaped ids are a no-op."""
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is None or sess.tenant != tenant:
                return False
            self._drop(sess)
            count = len(self._sessions)
        self._metric_gauge(registry, count)
        return True

    def lookup(self, session_id: str, registry=None) -> TenantSession:
        now = self._now()
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is not None and now - sess.last_used > self.ttl_s:
                self._drop(sess)
                sess = None
                expired = True
            else:
                expired = False
            count = len(self._sessions)
        if sess is None:
            self._metric_gauge(registry, count)
            if expired:
                raise SessionExpired(f"session {session_id} expired "
                                     f"(ttl {self.ttl_s:.0f}s)")
            raise UnknownSession(f"session {session_id} is not registered")
        return sess

    def _reap(self, now: float):
        # caller holds the lock
        dead = [s for s in self._sessions.values()
                if now - s.last_used > self.ttl_s]
        for s in dead:
            self._drop(s)

    def sweep(self, registry=None) -> int:
        """One GC sweep: reap every expired session and release its bundle
        bytes from the LRU budget NOW, instead of waiting for some client
        access to trip the reap-on-access path — an idle expired tenant's
        multi-MB bundle must not squat the shared budget (evicting healthy
        tenants) just because nobody happened to touch the server. Counts
        ``karpenter_solver_session_sweeps_total`` and refreshes the
        session/bytes gauges; returns the number of sessions reaped."""
        with self._lock:
            before = len(self._sessions)
            self._reap(self._now())
            count = len(self._sessions)
            total = self._total_bytes
        reaped = before - count
        if registry is not None:
            from karpenter_tpu.operator import metrics as m

            registry.counter(
                m.SOLVER_SESSION_SWEEPS,
                "periodic session-GC sweeps (expired sessions reaped and "
                "their bundle bytes released without a client access)",
            ).inc()
            registry.gauge(
                m.SOLVER_SESSIONS,
                "live tenant sessions on this solver service",
            ).set(count)
            registry.gauge(
                m.SOLVER_SESSION_CACHE_BYTES,
                "bytes of cached per-tenant solve bundles (LRU budget "
                "KARPENTER_SESSION_CACHE_BYTES)",
            ).set(total)
        return reaped

    def start_sweeper(self, interval_s: float | None = None, registry=None):
        """Run :meth:`sweep` every ``interval_s`` seconds (default
        ``KARPENTER_SESSION_SWEEP_S``, 60; <= 0 disables) on a daemon
        thread. Returns a ``threading.Event`` — set it to stop the
        sweeper — or None when disabled."""
        if interval_s is None:
            interval_s = env_float("KARPENTER_SESSION_SWEEP_S", 60.0)
        if interval_s <= 0:
            return None
        stop = threading.Event()

        def _loop():
            while not stop.wait(interval_s):
                self.sweep(registry=registry)

        t = threading.Thread(target=_loop, name="session-sweeper",
                             daemon=True)
        t.start()
        return stop

    def _drop(self, sess: TenantSession):
        # caller holds the lock
        self._sessions.pop(sess.id, None)
        if sess.bundle is not None:
            self._total_bytes -= sess.bundle_bytes
            sess.bundle = None
            sess.bundle_bytes = 0

    # -- admission (per-tenant in-flight budget) -------------------------

    @contextmanager
    def admit(self, sess: TenantSession, registry=None):
        with self._lock:
            n = self._inflight.get(sess.tenant, 0)
            if n >= self.inflight_budget:
                ok = False
            else:
                ok = True
                self._inflight[sess.tenant] = n + 1
        if not ok:
            if registry is not None:
                from karpenter_tpu.operator import metrics as m

                registry.counter(
                    m.SOLVER_ADMISSION_REJECTS,
                    "session solves rejected by the per-tenant in-flight "
                    "budget (backpressure, not queueing)",
                ).inc(tenant=sess.tenant)
            raise TenantBudgetExceeded(
                f"tenant {sess.tenant} already has {self.inflight_budget} "
                "solves in flight")
        try:
            yield
        finally:
            with self._lock:
                left = self._inflight.get(sess.tenant, 1) - 1
                if left <= 0:
                    # drop drained entries: tenant ids are client-supplied,
                    # and name churn must not grow this dict forever (the
                    # same stance as the SloTracker tenant cap)
                    self._inflight.pop(sess.tenant, None)
                else:
                    self._inflight[sess.tenant] = left

    # -- snapshot bundle maintenance -------------------------------------

    def apply(self, sess: TenantSession, arrays: dict, meta: dict,
              registry=None) -> dict:
        """Fence the request and produce the solve-ready args: a full
        upload replaces the session's bundle; a delta builds a PATCHED
        COPY and swaps it in under a fence re-check (swap-not-mutate: a
        dispatch already queued on the previous bundle — possibly parked
        in the coalescer window — never observes a membership or array
        change, and the expensive numpy work runs outside the registry
        lock so other tenants' requests don't serialize behind it).
        Raises a :class:`SessionError` subclass on every protocol
        violation (module docstring)."""
        seq = int(meta.get("seq", 0))
        mode = meta.get("mode", "full")
        now = self._now()
        if mode != "delta":
            # multi-MB conversion + byte sweep OUTSIDE the lock: holding
            # it here would serialize every other tenant's lookup/admit
            # behind each snapshot copy — inflating exactly the
            # cross-tenant p99 this service exists to bound
            full_args = {k: np.asarray(v) for k, v in arrays.items()}
            full_bytes = _nbytes(full_args)
            with self._lock:
                if self._sessions.get(sess.id) is not sess:
                    # dropped while the conversion ran unlocked (TTL reap,
                    # session-cap LRU, supersedes release): storing onto
                    # the orphan would add bytes _collect_evictions can
                    # never see again — permanent phantom budget pressure
                    raise SessionExpired(
                        f"session {sess.id} dropped during a full upload")
                if seq <= sess.last_seq:
                    raise OutOfOrderDelta(
                        f"seq {seq} <= last applied {sess.last_seq} for "
                        f"session {sess.id}")
                args = full_args
                self._store(sess, full_args, full_bytes)
                sess.full_uploads += 1
                hit_kind = None
                sess.last_seq = seq
                sess.last_used = now
                total = self._total_bytes
        else:
            with self._lock:
                if seq <= sess.last_seq:
                    raise OutOfOrderDelta(
                        f"seq {seq} <= last applied {sess.last_seq} for "
                        f"session {sess.id}")
                self._check_delta(sess, meta)
                self._bleed_check(sess, registry)
                base = sess.bundle
                base_seq = sess.last_seq
            # the splice copies happen UNLOCKED against the grabbed
            # reference; the swap below re-checks the fence, so a
            # concurrent apply on the same session (already a protocol
            # violation) resolves to a resync demand, never corruption
            args = self._build_patched(base, arrays, meta)
            with self._lock:
                if sess.last_seq != base_seq or sess.bundle is not base:
                    raise ResyncRequired(
                        f"session {sess.id} mutated concurrently with a "
                        "delta apply")
                sess.bundle = args
                sess.delta_rounds += 1
                hit_kind = "delta"
                sess.last_seq = seq
                sess.last_used = now
                total = self._total_bytes
        if registry is not None:
            from karpenter_tpu.operator import metrics as m

            if hit_kind is not None:
                registry.counter(
                    m.SOLVER_SESSION_CACHE_HITS,
                    "session solves served by patching the cached "
                    "per-tenant bundle (deltas, not re-uploads)",
                ).inc(tenant=sess.tenant, kind=hit_kind)
            else:
                registry.counter(
                    m.SOLVER_SESSION_CACHE_STORES,
                    "full snapshot uploads stored into the per-tenant "
                    "bundle cache",
                ).inc(tenant=sess.tenant)
            registry.gauge(
                m.SOLVER_SESSION_CACHE_BYTES,
                "bytes of cached per-tenant solve bundles (LRU budget "
                "KARPENTER_SESSION_CACHE_BYTES)",
            ).set(total)
        return args

    def _check_delta(self, sess: TenantSession, meta: dict):
        # caller holds the lock
        if sess.bundle is None:
            raise ResyncRequired(
                f"session {sess.id} holds no bundle (evicted or never "
                "uploaded)")
        base_seq = int(meta.get("base_seq", -1))
        if base_seq != sess.last_seq:
            raise ResyncRequired(
                f"delta base seq {base_seq} != last applied "
                f"{sess.last_seq} (journal gap)")
        journal = meta.get("journal")
        if journal is not None and any(e is None for e in journal):
            raise ResyncRequired("opaque journal entry in the delta window")

    def _bleed_check(self, sess: TenantSession, registry=None):
        """The cross-tenant-bleed assertion hook: the cached bundle's
        owner tag must match the session about to consume it."""
        ok = sess.bundle_tenant == sess.tenant
        if registry is not None:
            from karpenter_tpu.operator import metrics as m

            registry.counter(
                m.SOLVER_BLEED_CHECKS,
                "cross-tenant isolation assertions on cached bundles",
            ).inc(outcome="ok" if ok else "bleed")
        if not ok:
            from karpenter_tpu import obs

            obs.anomaly("cross-tenant-bleed", registry=registry,
                        tenant=sess.tenant,
                        bundle_tenant=str(sess.bundle_tenant))
            raise CrossTenantBleed(
                f"bundle tagged {sess.bundle_tenant!r} consumed by tenant "
                f"{sess.tenant!r}")
        return True

    @staticmethod
    def _build_patched(base: dict, arrays: dict, meta: dict) -> dict:
        """Patched bundle copy — pure, lock-free: the result is a NEW dict
        (unchanged keys share arrays; patched keys get spliced copies), so
        the previous bundle any in-flight dispatch holds stays
        bit-identical and membership-stable."""
        bundle = dict(base)
        patch = meta.get("patch") or {}
        for key, kind in patch.items():
            if kind == "rows":
                rows = arrays.get(key + ROWS_SUFFIX)
                vals = arrays.get(key + VALS_SUFFIX)
                old = bundle.get(key)
                if rows is None or vals is None or old is None:
                    raise ResyncRequired(f"row patch for {key} is missing "
                                         "its rows/vals/base")
                rows = np.asarray(rows)
                # negative indices would wrap silently and splice the
                # WRONG rows — reject both directions, never corrupt
                if rows.size and (int(rows.min()) < 0
                                  or int(rows.max()) >= old.shape[0]):
                    raise ResyncRequired(
                        f"row patch for {key} addresses rows outside "
                        f"[0, {old.shape[0]})")
                new = old.copy()
                try:
                    splice_rows(new, rows, np.asarray(vals))
                except ValueError as e:
                    raise ResyncRequired(str(e)) from e
                bundle[key] = new
            else:  # full replacement of one array
                val = arrays.get(key)
                old = bundle.get(key)
                if val is None:
                    raise ResyncRequired(f"replacement for {key} missing")
                val = np.asarray(val)
                if old is not None and (old.shape != val.shape
                                        or old.dtype != val.dtype):
                    raise ResyncRequired(
                        f"replacement for {key} changes the compiled "
                        f"family ({old.shape}/{old.dtype} -> "
                        f"{val.shape}/{val.dtype})")
                if old is None:
                    raise ResyncRequired(
                        f"replacement for {key} has no cached base")
                bundle[key] = val
        # shape-stable patches cannot change a bundle's size (key-set
        # changes go through a full re-upload — the client's shape-change
        # resync), so the byte accounting is invariant across deltas
        return bundle

    def _store(self, sess: TenantSession, args: dict, nbytes: int):
        # caller holds the lock; `nbytes` was computed outside it
        self._total_bytes -= sess.bundle_bytes
        sess.bundle = args
        sess.bundle_tenant = sess.tenant
        sess.bundle_bytes = nbytes
        self._total_bytes += sess.bundle_bytes
        # EXTEND, never replace: a concurrent store's victims must not be
        # lost before drain_evictions counts them onto the scrape
        self._evictions_pending.extend(self._collect_evictions(sess))

    def _collect_evictions(self, keep: TenantSession) -> list:
        # caller holds the lock; evicts oldest-last_used OTHER bundles
        # until the byte budget holds (the writer's own bundle survives)
        evicted = []
        while self._total_bytes > self.byte_budget:
            victims = [
                s for s in self._sessions.values()
                if s.bundle is not None and s is not keep
            ]
            if not victims:
                break
            victim = min(victims, key=lambda s: s.last_used)
            self._total_bytes -= victim.bundle_bytes
            victim.bundle = None
            victim.bundle_tenant = None
            victim.bundle_bytes = 0
            evicted.append(victim.tenant)
        return evicted

    def drain_evictions(self, registry=None) -> list:
        """Evicted-tenant list of the most recent store, counted onto the
        scrape (called by the service after releasing no locks of its
        own)."""
        with self._lock:
            evicted, self._evictions_pending = self._evictions_pending, []
        if evicted and registry is not None:
            from karpenter_tpu.operator import metrics as m

            c = registry.counter(
                m.SOLVER_SESSION_CACHE_EVICTIONS,
                "per-tenant bundles evicted by the LRU byte budget",
            )
            for tenant in evicted:
                c.inc(tenant=tenant)
        return evicted

    def verify_isolation(self, registry=None) -> list:
        """Sweep every live bundle's tenant tag (the test/perf-facing
        bleed hook); returns the list of violating session ids (empty =
        clean) and counts each check on the scrape."""
        with self._lock:
            pairs = [
                (s.id, s.tenant, s.bundle_tenant)
                for s in self._sessions.values()
                if s.bundle is not None
            ]
        bad = []
        if registry is not None:
            from karpenter_tpu.operator import metrics as m

            c = registry.counter(
                m.SOLVER_BLEED_CHECKS,
                "cross-tenant isolation assertions on cached bundles",
            )
        for sid, tenant, tag in pairs:
            ok = tenant == tag
            if registry is not None:
                c.inc(outcome="ok" if ok else "bleed")
            if not ok:
                bad.append(sid)
        return bad

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
            total = self._total_bytes
        return {
            "sessions": len(sessions),
            "bytes": total,
            "byte_budget": self.byte_budget,
            "tenants": sorted({s.tenant for s in sessions}),
            "full_uploads": sum(s.full_uploads for s in sessions),
            "delta_rounds": sum(s.delta_rounds for s in sessions),
        }

    def _metric_gauge(self, registry, count: int):
        if registry is None:
            return
        from karpenter_tpu.operator import metrics as m

        registry.gauge(
            m.SOLVER_SESSIONS, "live tenant sessions on this solver service",
        ).set(count)
