"""Gang (all-or-nothing pod-group) collection and co-location injection.

A gang is every pending pod sharing a ``karpenter.sh/pod-group``
annotation value (api/labels.py POD_GROUP_ANNOTATION). Two optional
annotations refine it:

* ``karpenter.sh/pod-group-min-member``: the group is only admissible once
  at least this many members are pending — fewer routes the whole group
  (reason ``oversize``) until the rest arrive, the PodGroup minMember
  semantics of the MPI/gang schedulers (arxiv 2603.22691).
* ``karpenter.sh/pod-group-topology``: a topology key (e.g. the zone
  label) all members must co-locate on — slice adjacency expressed through
  the EXISTING topology overlay: each member clone gets the solve-internal
  ``POD_GROUP_LABEL`` stamped and a required pod-affinity term on that key
  selecting the gang label, which the host Topology engine and the waves
  compiler already understand. Nothing new reaches the kernel.

Gang priority is the MAX of its members' effective priorities (a gang is
one schedulable unit; its most urgent member sets its tier), and the gang
solves atomically inside that tier (plane.py owns the trial/promote flow).
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
)

__all__ = ["Gang", "collect_gangs", "inject_colocation"]


class Gang:
    def __init__(self, name: str, pods: list, prio_of: dict):
        self.name = name
        self.pods = list(pods)
        self.priority = max(prio_of[p.uid] for p in pods)
        self.min_member = _min_member(pods)
        self.topology_key = _topology_key(pods)

    def __repr__(self):
        return (f"Gang({self.name}, pods={len(self.pods)}, "
                f"prio={self.priority}, min={self.min_member})")


def _min_member(pods) -> int:
    for p in pods:
        raw = p.metadata.annotations.get(wk.POD_GROUP_MIN_ANNOTATION)
        if raw is not None:
            try:
                return max(int(raw), 1)
            except (TypeError, ValueError):
                return 1
    return 1


def _topology_key(pods) -> str:
    for p in pods:
        key = p.metadata.annotations.get(wk.POD_GROUP_TOPOLOGY_ANNOTATION)
        if key:
            return key
    return ""


def collect_gangs(pods, prio_of: dict) -> tuple:
    """(gangs sorted by (-priority, name), loose pods in input order)."""
    by_name: dict = {}
    loose = []
    for p in pods:
        name = p.metadata.annotations.get(wk.POD_GROUP_ANNOTATION)
        if name:
            by_name.setdefault(name, []).append(p)
        else:
            loose.append(p)
    gangs = [Gang(name, members, prio_of) for name, members in by_name.items()]
    gangs.sort(key=lambda g: (-g.priority, g.name))
    return gangs, loose


def inject_colocation(gang: Gang, clones: list) -> list:
    """Stamp the gang label + the co-location affinity term onto the
    gang's CLONES (the originals never carry solve-internal fields). A
    gang without a topology key passes through untouched — atomicity alone
    needs no constraint."""
    if not gang.topology_key:
        return clones
    selector = LabelSelector(match_labels={wk.POD_GROUP_LABEL: gang.name})
    for c in clones:
        c.metadata.labels = {**c.metadata.labels,
                             wk.POD_GROUP_LABEL: gang.name}
        aff = c.affinity or Affinity()
        pa = aff.pod_affinity or PodAffinity()
        pa.required = list(pa.required) + [
            PodAffinityTerm(topology_key=gang.topology_key,
                            label_selector=selector)
        ]
        aff.pod_affinity = pa
        c.affinity = aff
    return clones
