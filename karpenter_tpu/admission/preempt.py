"""Preemption counterfactuals: evict lower tiers to admit a higher one.

When the tiered cascade leaves a high-tier pod unschedulable, this module
asks — per existing node — "if this node's evictable lower-tier victims
were gone, would the pod land on it?" as ONE batched counterfactual
dispatch with the exact row shape the consolidation probe compiles
(``ops/consolidate.py dispatch_counterfactual_rows``): the shared
tensorized snapshot plus per-row deltas, here an ``e_free`` capacity
release instead of a zeroed column. Probe answers are SEEDS: the winning
node is confirmed by a real simulation — the host admission pipeline
(``ExistingNode.add``: taints, ports, requirements, topology, float64
fit) against a fork whose victims' capacity is released — before any
eviction ships. Evictions go through the store's PDB-gated eviction
subresource (the same primitive the drain path uses), and the preemptor
is NOMINATED onto the freed node so the binder lands it as capacity
frees (pod.nominated_node_name, the reference's nomination protocol).

Victim candidate rules (the satellite contract):

* effective priority strictly below the preemptor's;
* reschedulable (daemonset/static/terminal pods never count);
* NOT ``preemption_policy="Never"`` — on either side: a Never PREEMPTOR
  never triggers the ladder, and a Never VICTIM is exempt from the set;
* PDB-respecting (a pod whose PDB allows zero disruptions is exempt, and
  the eviction subresource re-checks at execute — no TOCTOU eviction);
* no drain-in-flight double-eviction: nodes marked for deletion or
  deleting (an executing consolidation/drain command) never contribute
  victims — their pods are already being rescheduled — and nodes that won
  an earlier preemption this round leave the candidate pool (their freed
  capacity is promised to that preemptor).

Every dispatch records a replay capture on the ``preempt.dispatch`` seam
(obs/capsule.py), so an anomalous admission round yields an offline
bit-replayable capsule exactly like the consolidation probe's.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu import obs
from karpenter_tpu.admission.fork import fork_enode, fork_topology
from karpenter_tpu.admission.priority import preemption_policy_of
from karpenter_tpu.utils import pod as pod_util
from karpenter_tpu.utils import resources as resutil

__all__ = ["victim_sets", "probe_feasible", "probe_feasible_batch",
           "confirm", "execute_evictions", "PreemptionCandidate"]


class PreemptionCandidate:
    """One node's evictable victim bundle for one preemptor. Victims are
    kept in eviction order — lowest priority first (the scheduler's
    preemption heuristic), name-tie-broken for determinism — so the
    confirm stage can trim to the MINIMAL prefix that admits the pod."""

    def __init__(self, enode, victims: list, prio_of: dict):
        self.enode = enode
        self.victims = sorted(
            victims,
            key=lambda v: (prio_of.get(v.uid, 0), v.metadata.name))
        self.release = resutil.merge(
            *[v.effective_requests() for v in victims])
        # eviction-cost order mirroring the scheduler's preemption
        # heuristic: disturb the least-important, smallest victim set
        self.cost = (
            max(prio_of.get(v.uid, 0) for v in victims),
            len(victims),
            sum(self.release.values()),
        )

    def trimmed(self, k: int) -> "PreemptionCandidate":
        out = object.__new__(PreemptionCandidate)
        out.enode = self.enode
        out.victims = self.victims[:k]
        out.release = resutil.merge(
            *[v.effective_requests() for v in out.victims])
        out.cost = self.cost
        return out

    @property
    def node_name(self) -> str:
        return self.enode.state_node.name


def victim_sets(preemptor, enodes, prio_of: dict, classes: dict,
                pdb_limits, taken: set) -> list:
    """Per-node evictable victim bundles, cheapest first. ``taken`` holds
    node names already promised to earlier preemptors this round.

    ``prio_of`` covers the round's PENDING batch; bound victims are
    resolved here through the same PriorityClass matrix — defaulting them
    to 0 would turn higher-priority bound workloads into "lower-tier"
    victims, the exact inversion the strictly-lower contract forbids."""
    from karpenter_tpu.admission.priority import (
        default_class,
        resolve_priority,
    )

    my_prio = prio_of[preemptor.uid]
    dflt = default_class(classes)
    prio_of = dict(prio_of)

    def _prio(v) -> int:
        p = prio_of.get(v.uid)
        if p is None:
            p = prio_of[v.uid] = resolve_priority(v, classes, dflt)[0]
        return p

    out = []
    for en in enodes:
        sn = getattr(en, "state_node", None)
        if sn is None or not getattr(sn, "provider_id", ""):
            continue  # claim residuals and facades never host victims
        if sn.provider_id.startswith("claim://"):
            continue
        if sn.marked_for_deletion or sn.deleting():
            continue  # drain-in-flight: no double-eviction
        if sn.name in taken:
            continue
        victims = []
        for v in sn.pods.values():
            if _prio(v) >= my_prio:
                continue
            if not pod_util.is_reschedulable(v):
                continue
            if preemption_policy_of(v, classes) == "Never":
                continue  # Never victims are exempt from candidate sets
            if pdb_limits is not None and pdb_limits.can_evict(v) is not None:
                continue
            victims.append(v)
        if victims:
            out.append(PreemptionCandidate(en, victims, prio_of))
    out.sort(key=lambda c: c.cost)
    return out


def probe_feasible(preemptor, candidates: list, templates, its,
                   daemon_overhead=None) -> list | None:
    """One batched counterfactual dispatch over every candidate node:
    row i releases candidate i's victims on its own column and asks
    whether the preemptor lands WITHOUT opening a fresh bin (it was just
    proven unschedulable with every bin-opening option available, so
    landing == landing on freed capacity). Returns a bool list over
    ``candidates``, or None when the scenario is inexpressible (the
    caller then confirms candidates directly, cheapest first)."""
    from karpenter_tpu.obs import capsule as _capsule
    from karpenter_tpu.ops.consolidate import (
        _pow2,
        dispatch_counterfactual_rows,
    )
    from karpenter_tpu.ops.tensorize import (
        device_eligible,
        kernel_args,
        tensorize,
        tensorize_existing,
    )

    if not candidates:
        return []
    if not device_eligible(preemptor):
        return None
    try:
        import jax  # noqa: F401
    except Exception:
        return None
    enodes = [c.enode for c in candidates]
    snap = tensorize([preemptor], templates, its,
                     daemon_overhead=daemon_overhead)
    if snap.G != 1:
        return None
    esnap = tensorize_existing(snap, enodes)
    Gp = _pow2(snap.G)
    Ep = _pow2(esnap.E)
    Tp = _pow2(snap.T)
    shared = kernel_args(snap, esnap, Gp=Gp, Tp=Tp, Ep=Ep,
                         include_counts=False)
    R = len(snap.resources)
    rows = len(candidates)
    g_count_k = np.zeros((rows, Gp), dtype=np.int32)
    g_count_k[:, 0] = 1
    e_zero_cols = [None] * rows
    e_free = []
    free_col = np.empty(rows, dtype=np.int64)
    free_delta = np.zeros((rows, R), dtype=np.float32)
    for i, cand in enumerate(candidates):
        delta = np.zeros(R, dtype=np.float32)
        for r, v in cand.release.items():
            if r in snap.resources:
                delta[snap.resources.index(r)] = v
        e_free.append((i, delta))
        free_col[i] = i
        free_delta[i] = delta
    max_minv = int(snap.m_minv.max()) if snap.m_minv.size else 0
    with obs.span("preempt.dispatch", rows=rows, kind="device"):
        placed_g, used = dispatch_counterfactual_rows(
            shared, Gp, Ep, esnap.e_avail, max_minv, g_count_k,
            e_zero_cols, e_free=e_free)
    if _capsule.capture_enabled():
        inputs = dict(shared)
        inputs[_capsule.CF_PREFIX + "g_count_rows"] = g_count_k
        inputs[_capsule.CF_PREFIX + "e_avail"] = np.asarray(esnap.e_avail)
        inputs[_capsule.CF_PREFIX + "e_zero_idx"] = np.zeros(0, np.int64)
        inputs[_capsule.CF_PREFIX + "e_zero_len"] = np.full(
            rows, -1, dtype=np.int64)
        inputs[_capsule.CF_PREFIX + "e_free_col"] = free_col
        inputs[_capsule.CF_PREFIX + "e_free_delta"] = free_delta
        _capsule.record_capture(
            "preempt.dispatch", inputs,
            {"placed_g": placed_g, "used": used},
            engine="device", max_minv=max_minv, Gp=Gp, Ep=Ep)
    return [bool(placed_g[i, 0] >= 1 and used[i] == 0)
            for i in range(rows)]


def probe_feasible_batch(preemptors: list, cand_lists: list, templates,
                         its, daemon_overhead=None) -> list | None:
    """The whole eviction ladder's counterfactuals in ONE dispatch: every
    (preemptor, candidate-node) pair becomes one row of the shared
    ``dispatch_counterfactual_rows`` batch — the row releases that
    candidate's victims on its own column and activates only that
    preemptor's group in the count mask. Rows share one tensorized
    snapshot over ALL preemptors and the union of their candidate nodes,
    so a 16-preemptor round pays one kernel cadence instead of sixteen
    (the fused cluster round's preemption leg — deploy/README.md).

    Returns per-preemptor bool lists aligned with ``cand_lists``, or None
    when the batch is inexpressible (the caller probes per preemptor)."""
    from karpenter_tpu.obs import capsule as _capsule
    from karpenter_tpu.ops.consolidate import (
        _pow2,
        dispatch_counterfactual_rows,
    )
    from karpenter_tpu.ops.tensorize import (
        device_eligible,
        kernel_args,
        tensorize,
        tensorize_existing,
    )

    pairs = [(j, c) for j, cands in enumerate(cand_lists) for c in cands]
    if not pairs:
        return [[] for _ in cand_lists]
    if not all(device_eligible(p) for p in preemptors):
        return None
    try:
        import jax  # noqa: F401
    except Exception:
        return None
    snap = tensorize(list(preemptors), templates, its,
                     daemon_overhead=daemon_overhead)
    gidx = {p.uid: g for g, pods_g in enumerate(snap.groups)
            for p in pods_g}
    if any(p.uid not in gidx for p in preemptors):
        return None
    enodes, col_of = [], {}
    for _, cand in pairs:
        if id(cand.enode) not in col_of:
            col_of[id(cand.enode)] = len(enodes)
            enodes.append(cand.enode)
    esnap = tensorize_existing(snap, enodes)
    Gp = _pow2(snap.G)
    Ep = _pow2(esnap.E)
    Tp = _pow2(snap.T)
    shared = kernel_args(snap, esnap, Gp=Gp, Tp=Tp, Ep=Ep,
                         include_counts=False)
    R = len(snap.resources)
    rows = len(pairs)
    g_count_k = np.zeros((rows, Gp), dtype=np.int32)
    e_zero_cols = [None] * rows
    e_free = []
    free_col = np.empty(rows, dtype=np.int64)
    free_delta = np.zeros((rows, R), dtype=np.float32)
    for i, (j, cand) in enumerate(pairs):
        g_count_k[i, gidx[preemptors[j].uid]] = 1
        col = col_of[id(cand.enode)]
        delta = np.zeros(R, dtype=np.float32)
        for r, v in cand.release.items():
            if r in snap.resources:
                delta[snap.resources.index(r)] = v
        e_free.append((col, delta))
        free_col[i] = col
        free_delta[i] = delta
    max_minv = int(snap.m_minv.max()) if snap.m_minv.size else 0
    with obs.span("preempt.dispatch", rows=rows, kind="device",
                  preemptors=len(preemptors)):
        placed_g, used = dispatch_counterfactual_rows(
            shared, Gp, Ep, esnap.e_avail, max_minv, g_count_k,
            e_zero_cols, e_free=e_free)
    if _capsule.capture_enabled():
        inputs = dict(shared)
        inputs[_capsule.CF_PREFIX + "g_count_rows"] = g_count_k
        inputs[_capsule.CF_PREFIX + "e_avail"] = np.asarray(esnap.e_avail)
        inputs[_capsule.CF_PREFIX + "e_zero_idx"] = np.zeros(0, np.int64)
        inputs[_capsule.CF_PREFIX + "e_zero_len"] = np.full(
            rows, -1, dtype=np.int64)
        inputs[_capsule.CF_PREFIX + "e_free_col"] = free_col
        inputs[_capsule.CF_PREFIX + "e_free_delta"] = free_delta
        _capsule.record_capture(
            "preempt.dispatch", inputs,
            {"placed_g": placed_g, "used": used},
            engine="device", max_minv=max_minv, Gp=Gp, Ep=Ep)
    out = [[] for _ in cand_lists]
    for i, (j, _) in enumerate(pairs):
        g = gidx[preemptors[j].uid]
        out[j].append(bool(placed_g[i, g] >= 1 and used[i] == 0))
    return out


def confirm(preemptor, candidate: PreemptionCandidate, topology) -> bool:
    """The probe-confirm contract's real simulation: fork the node, add
    the victims' capacity back, and run the preemptor through the host
    admission pipeline. Victims still count in the forked topology's
    domain maps — conservative (an anti-affinity conflict with a
    to-be-evicted victim declines the preemption rather than racing it)."""
    topo = fork_topology(topology)
    node = fork_enode(candidate.enode, topo)
    node.cached_available = resutil.merge(
        dict(node.cached_available), candidate.release)
    clone = preemptor.clone()
    return node.add(clone) is None


def trim_and_confirm(preemptor, candidate: PreemptionCandidate,
                     topology) -> "PreemptionCandidate | None":
    """The MINIMAL confirmed victim set on this node: the shortest prefix
    of the eviction order (lowest priority first) whose release the real
    simulation confirms — the probe's full-bundle row is a feasibility
    seed, never the eviction warrant. None when even the full bundle
    fails the confirm (probe-vs-host disagreement). Feasibility is
    monotone in the prefix (more released capacity never hurts the
    admission pipeline), so a binary search pays O(log V) confirms —
    each confirm forks the round topology, which a linear walk over a
    many-victim node would repeat per step."""
    V = len(candidate.victims)
    if V == 0 or not confirm(preemptor, candidate, topology):
        return None
    lo, hi = 1, V  # invariant: hi confirms, prefixes < lo are untested
    while lo < hi:
        mid = (lo + hi) // 2
        if confirm(preemptor, candidate.trimmed(mid), topology):
            hi = mid
        else:
            lo = mid + 1
    return candidate.trimmed(hi)


def execute_evictions(store, candidate: PreemptionCandidate, preemptor,
                      recorder=None, registry=None) -> tuple:
    """Ship the confirmed preemption: evict every victim through the
    store's PDB-gated eviction subresource and — only when the WHOLE
    minimal set shipped — nominate the preemptor onto the freed node.
    Returns ``(evicted, complete)``: a PDB that closed since the filter
    ran aborts the remaining victims (no TOCTOU race), and an incomplete
    set must not nominate — the trimmed prefix was minimal by
    construction, so partial room cannot fit the preemptor (the already-
    evicted victims' capacity returns to the general pool next round)."""
    from karpenter_tpu.kube.store import NotFoundError, TooManyRequests
    from karpenter_tpu.operator import metrics as m

    evicted = 0
    complete = True
    for v in candidate.victims:
        try:
            store.evict(v)
        except TooManyRequests:
            complete = False
            break
        except NotFoundError:
            # the victim vanished since the filter ran (a concurrent
            # termination finished the job): its capacity is already
            # free — the set is still satisfied, nothing to evict or
            # publish for this slot
            continue
        evicted += 1
        if recorder is not None:
            recorder.publish(
                "Preempted",
                f"pod {v.key()} preempted by {preemptor.key()} "
                f"on {candidate.node_name}",
                obj=v,
            )
    if evicted and registry is not None:
        registry.counter(
            m.ADMISSION_EVICTIONS,
            "victim pods evicted by confirmed admission preemptions",
        ).inc(evicted)
    if complete:
        # a complete set nominates even at zero evictions (every victim
        # vanished on its own — the confirmed capacity is free either way)
        preemptor.nominated_node_name = candidate.node_name
        store.update("pods", preemptor)
    return evicted, complete
