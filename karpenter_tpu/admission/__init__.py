"""Admission plane: priority tiers, preemption, and gang placement.

The subsystem between the provisioner's pending-pod intake and the solver
(ISSUE 12). Three ladders, each with its own decision-ledger site
(obs/decisions.py) and a closed reason enum:

* **Tiered solve** (``plane.py``, ``admission.tier``): the pending batch
  partitions by effective pod priority (``priority.py`` owns the
  resolution matrix) and the existing batched pack runs as a CASCADE —
  high tiers first, each lower tier packing into the residual capacity of
  the same bundle. Residual reuse is literal: the shared ExistingNode
  objects are re-tensorized per tier with their accumulated placements,
  and prior tiers' claims join the existing-node axis through the
  ``residual.ClaimResidual`` adapter, so one compile family (the pow-2
  ladder) serves every tier.
* **Preemption** (``preempt.py``, ``admission.preempt``): a high-tier pod
  the cascade could not place builds a counterfactual batch over
  evictable victims — the exact row shape the consolidation probe
  dispatches (``ops/consolidate.py dispatch_counterfactual_rows``, grown
  an ``e_free`` release column) — confirms the winning node by real
  simulation (the host admission pipeline), and evicts through the
  store's PDB-gated eviction subresource the drain path uses.
* **Gang admission** (``gangs.py``, ``admission.gang``): annotation-keyed
  pod-groups place atomically. A gang solves against a FORKED copy of the
  round's state (``fork.py``); a fully-placed trial is promoted wholesale
  (no re-solve, no divergence window), anything less routes the whole
  gang to the pod-error surface with a per-group reason — a partial
  placement can never bind.

``oracle.py`` is the tiered-FFD host oracle the perf rows and the seeded
parity suite compare against. Operator docs: deploy/README.md
"Priority & gang admission".
"""

from karpenter_tpu.admission.plane import AdmissionPlane  # noqa: F401
from karpenter_tpu.admission.priority import (  # noqa: F401
    resolve_priority,
    partition_tiers,
)
from karpenter_tpu.admission.oracle import tiered_ffd_oracle  # noqa: F401

__all__ = [
    "AdmissionPlane",
    "resolve_priority",
    "partition_tiers",
    "tiered_ffd_oracle",
]
