"""Effective pod priority: the resolution matrix.

Mirror of the kube-apiserver's priority admission (priority plugin +
scheduling.k8s.io semantics), compressed to the fields our Pod model
carries (api/objects.py:231-233):

1. an explicit ``pod.priority`` wins outright (the apiserver stamps it);
2. else ``pod.priority_class_name`` resolves through the PriorityClass
   objects; a missing class falls back like an unset name;
3. else the cluster's global-default class (highest value wins a
   multi-default tie, then lexicographically-first name — deterministic
   where the apiserver's "newest" is not);
4. else priority 0.

Values resolved through a class are re-checked against the system-reserved
ranges (store admission rejects illegal CLASSES, but classes handed in as
plain dicts — tests, the perf harness — never passed admission): a
non-``system-`` class claiming the positive reserved band, or ANY class in
the negative reserved band, resolves to 0 with reason ``reserved-range``
instead of smuggling a system priority into the cascade.
"""

from __future__ import annotations

from karpenter_tpu.api.admission import (
    HIGHEST_USER_DEFINABLE_PRIORITY,
    SYSTEM_CLASS_PREFIX,
)

__all__ = [
    "resolve_priority",
    "default_class",
    "effective_priorities",
    "partition_tiers",
    "preemption_policy_of",
]


def default_class(classes: dict):
    """The global-default PriorityClass, or None. Ties (multiple defaults)
    break on (highest value, then name) so resolution is deterministic."""
    best = None
    for name in sorted(classes):
        pc = classes[name]
        if not getattr(pc, "global_default", False):
            continue
        if best is None or pc.value > best.value:
            best = pc
    return best


def _legal(value: int, class_name: str) -> bool:
    if value < -HIGHEST_USER_DEFINABLE_PRIORITY:
        return False  # negative system-reserved range: nobody's
    if value > HIGHEST_USER_DEFINABLE_PRIORITY:
        return class_name.startswith(SYSTEM_CLASS_PREFIX)
    return True


def resolve_priority(pod, classes: dict | None = None,
                     default=None) -> tuple:
    """(effective priority, reason) for one pod. ``classes`` maps class
    name -> PriorityClass; ``default`` is the pre-resolved global-default
    class (pass ``default_class(classes)`` — threaded separately so bulk
    callers resolve it once)."""
    classes = classes or {}
    if pod.priority is not None:
        return int(pod.priority), "spec"
    name = pod.priority_class_name or ""
    if name:
        pc = classes.get(name)
        if pc is not None:
            if not _legal(pc.value, name):
                return 0, "reserved-range"
            return int(pc.value), "class"
        # a named-but-missing class: the apiserver would have rejected the
        # pod at create; mid-flight deletions degrade to the default path
        if default is not None and _legal(default.value, default.name):
            return int(default.value), "missing-class-default"
        return 0, "missing-class"
    if default is not None:
        if not _legal(default.value, default.name):
            return 0, "reserved-range"
        return int(default.value), "default-class"
    return 0, "unset"


def preemption_policy_of(pod, classes: dict | None = None) -> str:
    """The pod's effective preemption policy: the spec field when set,
    else the policy of the class its priority resolved through, else ""
    (PreemptLowerPriority)."""
    if pod.preemption_policy:
        return pod.preemption_policy
    classes = classes or {}
    pc = classes.get(pod.priority_class_name or "")
    if pc is not None and getattr(pc, "preemption_policy", ""):
        return pc.preemption_policy
    return ""


def effective_priorities(pods, classes: dict | None = None) -> dict:
    """uid -> effective priority for a batch (one default-class resolve)."""
    classes = classes or {}
    dflt = default_class(classes)
    return {p.uid: resolve_priority(p, classes, dflt)[0] for p in pods}


def partition_tiers(pods, prio_of: dict) -> list:
    """[(priority, [pods])] in DESCENDING priority order; pod order within
    a tier preserves the input order (the FFD sort happens downstream)."""
    by_prio: dict = {}
    for p in pods:
        by_prio.setdefault(prio_of[p.uid], []).append(p)
    return [(prio, by_prio[prio]) for prio in sorted(by_prio, reverse=True)]
