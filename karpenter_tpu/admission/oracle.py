"""Tiered-FFD host oracle: the admission plane's parity reference.

A deliberately-plain sequential implementation of the admission
semantics — per-tier FFD (the reference scheduler loop, HostSolver) with
prior tiers' claims threaded as ``initial_claims``, gangs trialed on
forked state and promoted atomically — independent of plane.py's
orchestration code. The seeded parity suite pins the cascade's host rung
bit-identical to this oracle across 100+ mixes
(tests/test_priority_admission.py), and the perf rows gate the DEVICE
cascade's node count against it (≤ oracle + 2%, ``python -m perf
priority`` / ``bench.py --priority``).

The oracle owns no store and never preempts — it answers "how many nodes
does a faithful sequential tiered FFD open, and which pods land" for the
same inputs the cascade consumed.
"""

from __future__ import annotations

from karpenter_tpu.admission.fork import (
    fork_claim,
    fork_enode,
    fork_limits,
    fork_topology,
)
from karpenter_tpu.admission.gangs import collect_gangs, inject_colocation
from karpenter_tpu.admission.priority import (
    effective_priorities,
    partition_tiers,
)
from karpenter_tpu.models.scheduler import SchedulerResults, subtract_max
from karpenter_tpu.models.solver import HostSolver

__all__ = ["tiered_ffd_oracle", "debit_limits"]


def debit_limits(limits, new_claims):
    """Cross-tier nodepool-limit accounting (scheduler.go:292 subtractMax
    applied between solves): each finished tier's claims debit the
    remaining limits the next tier sees. Shared verb with plane.py so the
    cascade and the oracle can never drift on the arithmetic."""
    if not limits:
        return limits
    for claim in new_claims:
        pool = claim.template.nodepool_name
        if pool in limits and claim.instance_types:
            limits[pool] = subtract_max(limits[pool], claim.instance_types)
    return limits


def placed_uids(claims, enodes) -> set:
    """Every pod uid the given claims + existing nodes report placed —
    the ONE membership helper the cascade, the oracle, and the perf rows
    all share (ClaimResidual's empty scheduled_pods included), so a
    placement-reporting change can never desynchronize the parity gates."""
    placed = {p.uid for c in claims for p in c.pods}
    for node in enodes:
        placed.update(
            p.uid for p in getattr(node, "scheduled_pods", None) or [])
    return placed


def _complete(res, pods) -> bool:
    placed = placed_uids(res.new_claims, res.existing_nodes)
    return all(p.uid in placed for p in pods)


def tiered_ffd_oracle(pods, templates, its, *, classes=None,
                      topology=None, existing_nodes=(),
                      daemon_overhead=None, limits=None,
                      volume_topology=None):
    """(SchedulerResults, report) for the sequential per-tier host FFD."""
    classes = classes or {}
    prio_of = effective_priorities(pods, classes)
    gangs, loose = collect_gangs(pods, prio_of)
    gangs_by_prio: dict = {}
    for g in gangs:
        gangs_by_prio.setdefault(g.priority, []).append(g)
    tiers_loose = dict(partition_tiers(loose, prio_of))
    all_prios = sorted(set(tiers_loose) | set(gangs_by_prio), reverse=True)

    host = HostSolver()
    claims: list = []
    enodes = list(existing_nodes)
    limits = fork_limits(limits)
    errors: dict = {}
    report = {"tiers": len(all_prios), "gangs_placed": 0, "gangs_routed": 0}
    for prio in all_prios:
        for gang in gangs_by_prio.get(prio, ()):
            if len(gang.pods) < gang.min_member:
                for p in gang.pods:
                    errors[p.key()] = (
                        f'pod group "{gang.name}" below min-member '
                        f"({len(gang.pods)} < {gang.min_member})")
                report["gangs_routed"] += 1
                continue
            topo = fork_topology(topology)
            f_enodes = [fork_enode(en, topo) for en in enodes]
            f_claims = [fork_claim(c, topo) for c in claims]
            clones = inject_colocation(gang, [p.clone() for p in gang.pods])
            if gang.topology_key and topo is not None:
                for c in clones:
                    topo.update(c)
            res = host.solve(
                clones, templates, its, topology=topo,
                existing_nodes=f_enodes, daemon_overhead=daemon_overhead,
                limits=fork_limits(limits), initial_claims=f_claims,
                volume_topology=volume_topology,
            )
            if _complete(res, clones):
                new = [c for c in res.new_claims
                       if all(c is not fc for fc in f_claims)]
                originals = {p.uid: p for p in gang.pods}
                for c in res.new_claims:
                    c.pods = [originals.get(p.uid, p) for p in c.pods]
                for node in res.existing_nodes:
                    node.pods = [originals.get(p.uid, p) for p in node.pods]
                topology = topo
                enodes = f_enodes
                claims = f_claims + new
                limits = debit_limits(fork_limits(limits), new)
                report["gangs_placed"] += 1
            else:
                for p in gang.pods:
                    errors[p.key()] = (
                        f'pod group "{gang.name}" could not place atomically')
                report["gangs_routed"] += 1
        tier_pods = tiers_loose.get(prio, ())
        if not tier_pods:
            continue
        res = host.solve(
            list(tier_pods), templates, its, topology=topology,
            existing_nodes=enodes, daemon_overhead=daemon_overhead,
            limits=fork_limits(limits), initial_claims=claims,
            volume_topology=volume_topology,
        )
        new = [c for c in res.new_claims if all(c is not pc for pc in claims)]
        claims = claims + new
        limits = debit_limits(limits, new)
        errors.update(res.pod_errors)
    return SchedulerResults(
        new_claims=claims, existing_nodes=enodes, pod_errors=errors,
    ), report
