"""AdmissionPlane: the tiered cascade + gang + preemption orchestrator.

Sits between the provisioner's pending-pod intake and the solver
(provisioner.schedule routes live batches here when any pod carries a
priority marker or a gang annotation; disruption counterfactuals and
marker-free batches keep the plain single solve). One ``solve_round``:

1. resolve effective priorities (priority.py), collect gangs (gangs.py),
   partition into descending tiers;
2. per tier, gangs first (trial on forked state, promote atomically or
   route whole — ``admission.gang``), then the tier's loose pods through
   the EXISTING batched pack: the shared ExistingNode objects accumulate
   placements across tiers, and prior tiers' claims join the
   existing-node axis as ``ClaimResidual`` rows on the device rung (the
   ops/tensorize.py residual machinery) or as ``initial_claims`` on the
   host rung — so lower tiers pack into the residual capacity of the same
   bundle, one pow-2 compile family across tiers (``admission.tier``);
3. pods still unschedulable walk the preemption ladder in tier order
   (preempt.py: counterfactual batch → confirm-by-real-simulation →
   PDB-gated evictions + nomination — ``admission.preempt``).

Since ISSUE 19 a gang-free round FUSES step 2's per-tier cascade into
one device dispatch: the pack kernel's ``g_tier`` axis fences group
order so lower bands pack into the capacity higher bands left behind —
the cascade's residual handoff, device-resident (``_solve_fused``;
parity pinned by tests/test_fused_round.py, rung ``fused`` on
``admission.tier``). Gang rounds keep the cascade: each gang is its own
atomic dispatch. Step 3's victim probes batch the same way
(preempt.py ``probe_feasible_batch``). See deploy/README.md "Fused
cluster round" for the dispatch-cadence and parity contracts.

KARPENTER_ADMISSION=0 disables the whole plane (single-solve behavior);
KARPENTER_FUSED_ROUND=0 restores the per-band dispatch cascade;
KARPENTER_PREEMPTION=0 disables only the preemption ladder;
KARPENTER_PREEMPT_MAX (16) bounds preemptors examined per round and
KARPENTER_PREEMPT_CONFIRMS (4) confirming simulations per preemptor.
"""

from __future__ import annotations

from karpenter_tpu import obs
from karpenter_tpu.admission import preempt as _preempt
from karpenter_tpu.admission.fork import (
    fork_claim,
    fork_enode,
    fork_limits,
    fork_topology,
)
from karpenter_tpu.admission.gangs import collect_gangs, inject_colocation
from karpenter_tpu.admission.oracle import debit_limits, placed_uids
from karpenter_tpu.admission.priority import (
    default_class,
    partition_tiers,
    preemption_policy_of,
    resolve_priority,
)
from karpenter_tpu.admission.residual import ClaimResidual
from karpenter_tpu.api import labels as wk
from karpenter_tpu.models.scheduler import NullTopology, SchedulerResults
from karpenter_tpu.models.solver import HostSolver, TPUSolver
from karpenter_tpu.obs import decisions
from karpenter_tpu.utils.envknobs import env_bool as _env_bool
from karpenter_tpu.utils.envknobs import env_int as _env_int

__all__ = ["AdmissionPlane"]


def _enabled() -> bool:
    return _env_bool("KARPENTER_ADMISSION", True)


def _preempt_enabled() -> bool:
    return _env_bool("KARPENTER_PREEMPTION", True)


def _fused_enabled() -> bool:
    """KARPENTER_FUSED_ROUND (default on): collapse consecutive gang-free
    loose tiers into ONE device solve with the tier axis fencing residual
    capacity on device (deploy/README.md "Fused cluster round").
    KARPENTER_FUSED_ROUND=0 restores the per-tier cascade everywhere —
    the parity oracle the seeded suite pins the fused path against."""
    return _env_bool("KARPENTER_FUSED_ROUND", True)


class _State:
    """The cascade's mutable round state — what a gang trial forks and a
    successful trial promotes."""

    def __init__(self, topology, enodes, claims, limits):
        self.topology = topology
        self.enodes = list(enodes)
        self.claims = list(claims)
        self.limits = limits


class AdmissionPlane:
    def __init__(self, store=None, registry=None, recorder=None, log=None):
        self.store = store
        self.registry = registry
        self.recorder = recorder
        self.log = log

    # -- engagement -------------------------------------------------------
    def engages(self, pods) -> bool:
        """True when the batch carries any admission marker — a priority
        field, a named class, a gang annotation, or (with a store) a
        global-default PriorityClass that would tier the batch."""
        if not _enabled() or not pods:
            return False
        for p in pods:
            if p.priority is not None or p.priority_class_name:
                return True
            if p.metadata.annotations.get(wk.POD_GROUP_ANNOTATION):
                return True
        if self.store is not None:
            for pc in self.store.list("priorityclasses"):
                if pc.global_default and pc.value != 0:
                    return True
        return False

    # -- the round --------------------------------------------------------
    def solve_round(self, solver, pods, templates, its, *, topology=None,
                    existing_nodes=(), daemon_overhead=None, limits=None,
                    volume_topology=None) -> SchedulerResults:
        classes = (
            {pc.name: pc for pc in self.store.list("priorityclasses")}
            if self.store is not None else {}
        )
        dflt = default_class(classes)
        prio_of = {
            p.uid: resolve_priority(p, classes, dflt)[0] for p in pods
        }
        gangs, loose = collect_gangs(pods, prio_of)
        gangs_by_prio: dict = {}
        for g in gangs:
            gangs_by_prio.setdefault(g.priority, []).append(g)
        tiers_loose = dict(partition_tiers(loose, prio_of))
        all_prios = sorted(set(tiers_loose) | set(gangs_by_prio),
                           reverse=True)

        state = _State(topology, existing_nodes, [], fork_limits(limits))
        errors: dict = {}
        report = {
            "tiers": len(all_prios), "gangs_placed": 0, "gangs_routed": 0,
            "preemptions": 0, "evictions": 0, "preempt_declined": 0,
            "preempt_unconfirmed": 0,
            # host-routed pods aggregated across every COMMITTED inner
            # solve (tier solves, mop-ups, promoted gang trials): the
            # solver's last_device_stats only reflects its final call, so
            # the provisioner's accounting reads this instead
            "host_routed": {},
            # solver.solve cadences this round paid (the fused round's
            # headline: >=2 loose tiers collapse to 1; gangs/preempt pay
            # their own) — perf surfaces this as dispatches_per_round
            "solve_dispatches": 0,
            "fused_runs": 0,
        }
        unplaced: list = []  # (priority, pod) after its tier's solve
        # fused round (deploy/README.md "Fused cluster round"): a
        # gang-free round's loose tiers collapse into ONE device dispatch
        # with the tier axis fencing residual capacity on device.
        # Gang-bearing rounds keep the cascade — each gang is its own
        # atomic dispatch so the round can never reach one dispatch, and
        # the fused scan's open-bin view of higher-tier residuals risks
        # the ±1-bin FFD noise on the gang interleave for a one-dispatch
        # saving; topology-bearing rounds keep the cascade (the waves
        # path ignores tier_of); the host rung keeps the cascade (its
        # FFD loop has no tier axis).
        has_topology = bool(getattr(
            topology, "has_groups",
            topology is not None and not isinstance(topology, NullTopology)))
        fuse = (_fused_enabled() and isinstance(solver, TPUSolver)
                and not has_topology and not gangs_by_prio)
        pending: list = []  # consecutive gang-free prios awaiting one solve

        def flush():
            if not pending:
                return
            run = {prio: list(tiers_loose[prio]) for prio in pending}
            pending.clear()
            if len(run) == 1:
                ((prio, tier_pods),) = run.items()
                missed = self._solve_tier(
                    solver, tier_pods, state, templates, its,
                    daemon_overhead, volume_topology, errors, report)
                unplaced.extend((prio, p) for p in missed)
            else:
                report["fused_runs"] += 1
                unplaced.extend(self._solve_fused(
                    solver, run, state, templates, its, daemon_overhead,
                    volume_topology, errors, report))

        for prio in all_prios:
            gangs_here = gangs_by_prio.get(prio, ())
            if gangs_here:
                flush()
            for gang in gangs_here:
                self._solve_gang(solver, gang, state, templates, its,
                                 daemon_overhead, volume_topology, errors,
                                 report)
            tier_pods = tiers_loose.get(prio, ())
            if not tier_pods:
                continue
            if fuse:
                pending.append(prio)
                continue
            missed = self._solve_tier(
                solver, list(tier_pods), state, templates, its,
                daemon_overhead, volume_topology, errors, report)
            unplaced.extend((prio, p) for p in missed)
        flush()
        decisions.record_decision(
            "admission.tier",
            "fused" if report["fused_runs"]
            else ("cascade" if len(all_prios) > 1 else "single"),
            "ok" if len(all_prios) > 1 else "single-tier",
            registry=self.registry)

        if unplaced and self.store is not None and _preempt_enabled():
            with obs.span("admission.preempt",
                          preemptors=len(unplaced)):
                self._preempt_round(unplaced, prio_of, classes, state,
                                    templates, its, daemon_overhead,
                                    errors, report)

        results = SchedulerResults(
            new_claims=state.claims,
            existing_nodes=list(state.enodes),
            pod_errors=errors,
        )
        results.admission = report
        return results

    @staticmethod
    def _note_routed(solver, report):
        """Fold the last inner solve's host-routed reasons into the
        round's aggregate (one dict across the whole cascade)."""
        routed = (getattr(solver, "last_device_stats", None)
                  or {}).get("host_routed") or {}
        agg = report["host_routed"]
        for reason, n in routed.items():
            if n:
                agg[reason] = agg.get(reason, 0) + n

    # -- one tier's loose pods -------------------------------------------
    def _solve_tier(self, solver, tier_pods, state, templates, its,
                    daemon_overhead, volume_topology, errors,
                    report) -> list:
        """Solve one tier into the shared bundle; returns the tier's
        unplaced pods (in input order)."""
        device_rung = isinstance(solver, TPUSolver)
        residuals = []
        if device_rung:
            residuals = [ClaimResidual(c) for c in state.claims]
            report["solve_dispatches"] += 1
            res = solver.solve(
                tier_pods, templates, its, topology=state.topology,
                existing_nodes=list(state.enodes) + residuals,
                daemon_overhead=daemon_overhead,
                limits=fork_limits(state.limits),
                volume_topology=volume_topology,
            )
            self._note_routed(solver, report)
            new = [c for c in res.new_claims
                   if all(c is not r.claim for r in residuals)]
            originals = {p.uid: p for p in tier_pods}
            mopup = []
            for r in residuals:
                mopup.extend(r.fold(originals))
            if mopup:
                # the exact re-admission refused a device residual commit
                # (merged-requirement narrowing the decode approximates):
                # one host mop-up seeded with every claim settles them.
                # The tier's OWN new claims must debit the limit fork
                # first — Scheduler never charges initial_claims, so an
                # undebited fork would let the mop-up overshoot the pool
                res2 = HostSolver().solve(
                    mopup, templates, its, topology=state.topology,
                    existing_nodes=list(state.enodes),
                    daemon_overhead=daemon_overhead,
                    limits=debit_limits(fork_limits(state.limits), new),
                    initial_claims=state.claims + new,
                    volume_topology=volume_topology,
                )
                new.extend(c for c in res2.new_claims
                           if all(c is not pc
                                  for pc in state.claims + new))
                errors.update(res2.pod_errors)
        else:
            res = solver.solve(
                tier_pods, templates, its, topology=state.topology,
                existing_nodes=list(state.enodes),
                daemon_overhead=daemon_overhead,
                limits=fork_limits(state.limits),
                initial_claims=state.claims,
                volume_topology=volume_topology,
            )
            new = [c for c in res.new_claims
                   if all(c is not pc for pc in state.claims)]
        state.claims.extend(new)
        state.limits = debit_limits(state.limits, new)
        errors.update(res.pod_errors)
        placed = placed_uids(state.claims, state.enodes)
        return [p for p in tier_pods if p.uid not in placed]

    # -- a fused run of gang-free tiers ----------------------------------
    def _solve_fused(self, solver, run, state, templates, its,
                     daemon_overhead, volume_topology, errors,
                     report) -> list:
        """All of ``run``'s tiers in ONE device dispatch: the tier axis
        (``tensorize(..., tier_of=...)``) orders the scan tier-major, so
        higher tiers consume shared and residual capacity first — the
        fence the cascade paid one dispatch per tier for now happens on
        device (deploy/README.md "Fused cluster round"). The mop-up of
        refused residual commits stays a single host pass, re-admitting
        tier-major so precedence survives there too. Returns the run's
        unplaced pods as (priority, pod) for the preemption ladder."""
        prios = sorted(run, reverse=True)
        # dense ranks, higher priority -> higher tier rank; rank 0 is the
        # lowest tier of THIS run, which is all the scan ordering needs
        rank = {prio: len(prios) - 1 - i for i, prio in enumerate(prios)}
        all_pods = [p for prio in prios for p in run[prio]]
        tier_of = {p.uid: rank[prio]
                   for prio in prios for p in run[prio]}
        residuals = [ClaimResidual(c) for c in state.claims]
        report["solve_dispatches"] += 1
        res = solver.solve(
            all_pods, templates, its, topology=state.topology,
            existing_nodes=list(state.enodes) + residuals,
            daemon_overhead=daemon_overhead,
            limits=fork_limits(state.limits),
            volume_topology=volume_topology,
            tier_of=tier_of,
        )
        self._note_routed(solver, report)
        new = [c for c in res.new_claims
               if all(c is not r.claim for r in residuals)]
        originals = {p.uid: p for p in all_pods}
        mopup = []
        for r in residuals:
            mopup.extend(r.fold(originals))
        if mopup:
            # same fork/debit discipline as _solve_tier's mop-up, but the
            # refused pods must queue tier-major or the host FFD would
            # hand a low tier capacity a high tier was refused over
            mopup.sort(key=lambda p: -tier_of.get(p.uid, 0))
            res2 = HostSolver().solve(
                mopup, templates, its, topology=state.topology,
                existing_nodes=list(state.enodes),
                daemon_overhead=daemon_overhead,
                limits=debit_limits(fork_limits(state.limits), new),
                initial_claims=state.claims + new,
                volume_topology=volume_topology,
            )
            new.extend(c for c in res2.new_claims
                       if all(c is not pc for pc in state.claims + new))
            errors.update(res2.pod_errors)
        state.claims.extend(new)
        state.limits = debit_limits(state.limits, new)
        errors.update(res.pod_errors)
        placed = placed_uids(state.claims, state.enodes)
        return [(prio, p) for prio in prios for p in run[prio]
                if p.uid not in placed]

    # -- one gang ---------------------------------------------------------
    def _solve_gang(self, solver, gang, state, templates, its,
                    daemon_overhead, volume_topology, errors, report):
        if len(gang.pods) < gang.min_member:
            self._route_gang(gang, "oversize", errors, report,
                             f"below min-member ({len(gang.pods)} < "
                             f"{gang.min_member})")
            return
        topo = fork_topology(state.topology)
        f_enodes = [fork_enode(en, topo) for en in state.enodes]
        f_claims = [fork_claim(c, topo) for c in state.claims]
        clones = inject_colocation(gang, [p.clone() for p in gang.pods])
        if gang.topology_key and topo is not None:
            # the injected co-location affinity exists only on the clones;
            # the round's topology was built over the originals, so the
            # gang's groups must register on the FORK or the constraint is
            # silently inert (promotion carries the registration forward)
            for c in clones:
                topo.update(c)
        device_rung = isinstance(solver, TPUSolver)
        try:
            if device_rung:
                residuals = [ClaimResidual(c) for c in f_claims]
                report["solve_dispatches"] += 1
                res = solver.solve(
                    clones, templates, its, topology=topo,
                    existing_nodes=f_enodes + residuals,
                    daemon_overhead=daemon_overhead,
                    limits=fork_limits(state.limits),
                    volume_topology=volume_topology,
                )
                new = [c for c in res.new_claims
                       if all(c is not r.claim for r in residuals)]
                for r in residuals:
                    if r.fold():
                        # a refused fold means the trial was NOT fully
                        # placed — the residual's optimistic capacity
                        # over-promised, a capacity event (benign reason),
                        # not a trial malfunction
                        self._route_gang(gang, "infeasible", errors,
                                         report, "residual fold refused")
                        return
            else:
                res = solver.solve(
                    clones, templates, its, topology=topo,
                    existing_nodes=f_enodes,
                    daemon_overhead=daemon_overhead,
                    limits=fork_limits(state.limits),
                    initial_claims=f_claims,
                    volume_topology=volume_topology,
                )
                new = [c for c in res.new_claims
                       if all(c is not fc for fc in f_claims)]
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "gang trial solve failed; routing group %s", gang.name,
                exc_info=True)
            self._route_gang(gang, "trial-error", errors, report,
                             "trial solve raised")
            return
        placed = placed_uids(f_claims + new, f_enodes)
        if all(p.uid in placed for p in clones):
            # promote the trial wholesale: the fork becomes the live state
            originals = {p.uid: p for p in gang.pods}
            for c in f_claims + new:
                c.pods = [originals.get(p.uid, p) for p in c.pods]
            for node in f_enodes:
                node.pods = [originals.get(p.uid, p) for p in node.pods]
            state.topology = topo
            state.enodes = f_enodes
            state.claims = f_claims + new
            state.limits = debit_limits(fork_limits(state.limits), new)
            report["gangs_placed"] += 1
            self._note_routed(solver, report)  # the trial IS the commit
            decisions.record_decision("admission.gang", "atomic", "ok",
                                      registry=self.registry)
        else:
            starved = any("exceed limits" in str(e)
                          for e in res.pod_errors.values())
            self._route_gang(
                gang, "budget-starved" if starved else "infeasible",
                errors, report, "could not place atomically")

    def _route_gang(self, gang, reason, errors, report, why):
        for p in gang.pods:
            errors[p.key()] = f'pod group "{gang.name}" host-routed: {why}'
        report["gangs_routed"] += 1
        decisions.record_decision("admission.gang", "routed", reason,
                                  registry=self.registry)

    # -- preemption -------------------------------------------------------
    def _preempt_round(self, unplaced, prio_of, classes, state, templates,
                       its, daemon_overhead, errors, report):
        from karpenter_tpu.operator import metrics as m
        from karpenter_tpu.utils.pdb import PdbLimits

        pdb_limits = PdbLimits(self.store)
        taken: set = set()
        max_preempts = _env_int("KARPENTER_PREEMPT_MAX", 16, minimum=0)
        max_confirms = _env_int("KARPENTER_PREEMPT_CONFIRMS", 4, minimum=1)
        ladder = sorted(unplaced, key=lambda t: -t[0])[:max_preempts]
        probes = self._batch_probe(ladder, prio_of, classes, state,
                                   templates, its, daemon_overhead,
                                   pdb_limits)
        for prio, pod in ladder:
            outcome = self._preempt_one(
                pod, prio_of, classes, state, templates, its,
                daemon_overhead, pdb_limits, taken, max_confirms, errors,
                report, probe=probes.get(pod.uid))
            if self.registry is not None:
                self.registry.counter(
                    m.ADMISSION_PREEMPTIONS,
                    "admission preemption ladder outcomes",
                ).inc(outcome=outcome)

    def _batch_probe(self, ladder, prio_of, classes, state, templates,
                     its, daemon_overhead, pdb_limits) -> dict:
        """ONE shared counterfactual dispatch for the whole preemption
        ladder (the fused round's preemption leg): every examined
        preemptor's candidate rows fold into one
        ``dispatch_counterfactual_rows`` batch instead of one dispatch
        per preemptor. Candidates are gathered taken-blind — the batch
        cannot know which nodes earlier preemptors will win, and
        ``taken`` only ever EXCLUDES nodes, so re-filtering at selection
        time in ``_preempt_one`` is equivalent to the sequential gather.
        Returns {pod uid: (candidates, feasible-list-or-None)}."""
        pods = [pod for _, pod in ladder
                if preemption_policy_of(pod, classes) != "Never"]
        if not pods:
            return {}
        cand_lists = [
            _preempt.victim_sets(pod, state.enodes, prio_of, classes,
                                 pdb_limits, set())
            for pod in pods]
        feas_lists = None
        if sum(1 for c in cand_lists if c) >= 2:
            try:
                feas_lists = _preempt.probe_feasible_batch(
                    pods, cand_lists, templates, its,
                    daemon_overhead=daemon_overhead)
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "batched preemption probe failed; probing per "
                    "preemptor", exc_info=True)
        if feas_lists is None:
            feas_lists = [None] * len(pods)
        return {pod.uid: (cands, feas)
                for pod, cands, feas in zip(pods, cand_lists, feas_lists)}

    def _preempt_one(self, pod, prio_of, classes, state, templates,
                     its, daemon_overhead, pdb_limits, taken, max_confirms,
                     errors, report, probe=None) -> str:
        if preemption_policy_of(pod, classes) == "Never":
            decisions.record_decision("admission.preempt", "skipped",
                                      "policy-never",
                                      registry=self.registry)
            return "skipped"
        feas = None
        have_feas = False
        if probe is not None:
            cands, feas = probe
            if feas is not None:
                have_feas = True
                kept = [(c, ok) for c, ok in zip(cands, feas)
                        if c.node_name not in taken]
                cands = [c for c, _ in kept]
                feas = [ok for _, ok in kept]
            else:
                cands = [c for c in cands if c.node_name not in taken]
        else:
            cands = _preempt.victim_sets(pod, state.enodes, prio_of,
                                         classes, pdb_limits, taken)
        if not cands:
            decisions.record_decision("admission.preempt", "skipped",
                                      "no-victims", registry=self.registry)
            return "skipped"
        probe_error = False
        if not have_feas:
            try:
                feas = _preempt.probe_feasible(
                    pod, cands, templates, its,
                    daemon_overhead=daemon_overhead)
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "preemption probe failed; confirming sequentially",
                    exc_info=True)
                # no verdict yet: the ladder records exactly ONE per
                # examined preemptor — the probe-error cause rides a
                # declining verdict below; a confirm that still lands
                # records confirmed/ok
                probe_error = True
                feas = None
        # probe misses stay misses (seeds are trusted negative only up to
        # the bounded confirm budget below); inexpressible probes confirm
        # the cheapest candidates directly — the reference-cost path
        ordered = (
            [c for c, ok in zip(cands, feas) if ok]
            if feas is not None else list(cands)
        )
        if not ordered:
            decisions.record_decision(
                "admission.preempt", "declined",
                "probe-error" if probe_error else "no-feasible-node",
                registry=self.registry)
            report["preempt_declined"] += 1
            return "declined"
        confirmed = None
        for cand in ordered[:max_confirms]:
            trimmed = _preempt.trim_and_confirm(pod, cand, state.topology)
            if trimmed is not None:
                confirmed = trimmed
                break
            report["preempt_unconfirmed"] += 1
        if confirmed is None:
            decisions.record_decision(
                "admission.preempt", "declined",
                "probe-error" if probe_error else "confirm-failed",
                registry=self.registry)
            report["preempt_declined"] += 1
            return "declined"
        evicted, complete = _preempt.execute_evictions(
            self.store, confirmed, pod, recorder=self.recorder,
            registry=self.registry)
        report["evictions"] += evicted
        if not complete:
            # a PDB that closed mid-set: whatever shipped stays shipped
            # (its capacity returns to the pool) but the preemptor is NOT
            # nominated and keeps its scheduling error for the next round
            decisions.record_decision("admission.preempt", "declined",
                                      "pdb-blocked", registry=self.registry)
            report["preempt_declined"] += 1
            return "declined"
        taken.add(confirmed.node_name)
        report["preemptions"] += 1
        # the preemptor is nominated, not failed: drop its error so the
        # round doesn't publish FailedScheduling for a pod mid-preemption
        errors.pop(pod.key(), None)
        decisions.record_decision("admission.preempt", "confirmed", "ok",
                                  registry=self.registry)
        return "confirmed"
