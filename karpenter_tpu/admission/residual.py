"""ClaimResidual: a higher-tier claim as a lower tier's scheduling target.

The cascade's residual reuse (ISSUE 12 tentpole): a claim opened by an
earlier tier joins the NEXT tier's existing-node axis, so the device pack
(``ops/tensorize.py tensorize_existing`` — the same machinery real nodes
ride) can fill its remaining capacity instead of opening fresh bins.

Soundness stance (a claim is a RANGE of instance types, a node is one
concrete machine):

* the tensorized availability is the MAX allocatable over the claim's
  remaining instance types net of its accumulated requests — the FFD's
  own claim-capacity rule (models/inflight.py: "its effective capacity is
  the max over remaining types"), so the kernel packs residuals exactly
  as aggressively as the host loop would;
* the strict existing-node admission (every group-required key must be
  defined on the claim's requirement set) refuses pods whose keys the
  claim never constrained — the safe direction (they open their own bin
  or retry on the host);
* device-committed pods are NOT bound by the decode alone: ``fold()``
  re-admits each through ``InFlightNodeClaim.add`` — the exact host
  primitive, which narrows the claim's instance types and rejects any
  pod the optimistic capacity over-promised — and returns the rejects
  (the plane mops those up host-side). Topology was already committed by
  the solver's decode for these pods, so the fold swaps in a NullTopology
  to avoid double-recording.

The host pass inside ``solver.solve`` needs no adapter logic at all:
``add`` delegates straight to ``claim.add`` (bit-exact FFD semantics).
"""

from __future__ import annotations

from karpenter_tpu.models.scheduler import NullTopology

__all__ = ["ClaimResidual"]


class _ResidualState:
    """The state_node facade tensorize_existing reads."""

    def __init__(self, claim):
        self._claim = claim
        self.provider_id = f"claim://{claim.hostname}"

    @property
    def name(self) -> str:
        return self._claim.hostname

    @property
    def hostname(self) -> str:
        return self._claim.hostname

    @property
    def pods(self):
        return self._claim.pods  # len() = fill priority (e_npods)

    def taints(self):
        return list(self._claim.template.taints)


class ClaimResidual:
    def __init__(self, claim):
        self.claim = claim
        self.state_node = _ResidualState(claim)
        # device decode (ecommit) mutates these three in place; fold()
        # replays `pods` through claim.add and discards the rest — the
        # claim's own accounting is authoritative
        self.pods: list = []
        self.requests = dict(claim.requests)
        self.requirements = claim.requirements
        self.cached_available = self._max_alloc()
        self._host_added: list = []

    def _max_alloc(self) -> dict:
        """Per-resource MAX allocatable over the claim's remaining types —
        the FFD's effective claim capacity (models/inflight.py), compiled
        as the residual's availability; fold()'s exact re-admission is
        what keeps the optimism honest."""
        out: dict = {}
        for it in self.claim.instance_types:
            for r, v in it.allocatable().items():
                if v > out.get(r, 0.0):
                    out[r] = v
        return out

    # -- host-pass interface (Scheduler._add tries existing nodes first) --
    @property
    def scheduled_pods(self) -> list:
        # the plane folds device commits into the claim and drops the
        # residual before results surface; never report pods twice
        return []

    def add(self, pod):
        err = self.claim.add(pod)
        if err is None:
            self._host_added.append(pod)
        return err

    # -- decode-commit fold ----------------------------------------------
    def fold(self, originals: dict | None = None) -> list:
        """Re-admit device-committed pods through the claim's exact add
        (NullTopology — the solver's decode already recorded topology for
        them); remap host-added clones to the caller's originals. Returns
        the pods the exact check refused."""
        fails = []
        if self.pods:
            saved = self.claim.topology
            self.claim.topology = NullTopology()
            try:
                for p in self.pods:
                    if self.claim.add(p) is not None:
                        fails.append(p)
            finally:
                self.claim.topology = saved
            self.pods = []
        if originals and (self._host_added or self.claim.pods):
            self.claim.pods = [
                originals.get(p.uid, p) for p in self.claim.pods
            ]
        self._host_added = []
        return fails
