"""State forking for gang trials and preemption confirms.

A gang must place atomically, and ``solver.solve`` commits placements onto
the existing nodes / topology / claims IN PLACE even when later pods fail
— so atomicity is achieved by solving against a FORK of the round's
mutable state and, on full success, PROMOTING the fork wholesale (the
trial IS the commit; there is no re-solve whose tie-breaks could diverge).
A failed trial is simply dropped.

What forks, and how:

* **Topology** — shallow copy with the group registries deep-copied
  (TopologyGroup holds only selectors/filters/count dicts); the cluster
  view stays shared by reference (read-only), memo/owner indexes reset.
* **ExistingNode** — ``ExistingNode.fork`` (the disruption-simulation
  primitive) rebound to the forked topology, with the pods placed by
  EARLIER tiers carried over (fork() clears them by design for
  counterfactuals; a cascade fork must preserve them so promotion loses
  nothing).
* **InFlightNodeClaim** — field-wise copy sharing the immutable template
  and taints; ``add`` replaces requirements/requests/instance_types rather
  than mutating, so sharing the current objects is safe.
"""

from __future__ import annotations

import copy

from karpenter_tpu.models.inflight import InFlightNodeClaim

__all__ = ["fork_topology", "fork_enode", "fork_claim", "fork_limits"]


def fork_topology(topology):
    if topology is None or not hasattr(topology, "topologies"):
        # a constraint-free round (None, or an already-stateless
        # NullTopology): nothing to fork — hand back a stateless hook so
        # ExistingNode.fork's register() call always has a receiver
        from karpenter_tpu.models.scheduler import NullTopology

        return topology if topology is not None else NullTopology()
    out = copy.copy(topology)
    out.topologies = copy.deepcopy(topology.topologies)
    out.inverse_topologies = copy.deepcopy(topology.inverse_topologies)
    out.domains = {k: set(v) for k, v in topology.domains.items()}
    out.excluded_pods = set(topology.excluded_pods)
    out._sel_memo = {}
    # owner groups re-resolve lazily: update() on a fork only ever ADDS —
    # never un-registers a live group — which is exactly a trial's contract
    out._owner_tgs = {}
    return out


def fork_enode(en, topology):
    out = en.fork(topology)
    # fork() starts pods empty (per-simulation counterfactual); the
    # cascade's fork must carry the placements earlier tiers committed so
    # a promoted trial still reports them (requests already carried)
    out.pods = list(en.pods)
    return out


def fork_claim(claim, topology):
    out = object.__new__(InFlightNodeClaim)
    out.template = claim.template
    out.topology = topology
    out.daemon_resources = dict(claim.daemon_resources)
    out.instance_types = list(claim.instance_types)
    out.pods = list(claim.pods)
    out.requests = dict(claim.requests)
    out.requirements = claim.requirements.copy()
    out.hostname = claim.hostname
    out.taints = claim.taints
    out.host_ports = claim.host_ports.copy()
    return out


def fork_limits(limits):
    if not limits:
        return limits
    return {pool: dict(rem) for pool, rem in limits.items()}
