"""Clock abstraction: real time for the operator, fake time for tests
(the reference's envtest suites inject a fake clock the same way)."""

from __future__ import annotations

import threading
import time as _time


class Clock:
    def now(self) -> float:
        return _time.time()

    def sleep(self, seconds: float):
        _time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 1_700_000_000.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float):
        self.step(seconds)

    def step(self, seconds: float):
        with self._lock:
            self._now += seconds

    def set(self, t: float):
        with self._lock:
            self._now = t
