"""Minimal 5-field cron schedule (UTC) for disruption-budget windows.

The reference parses Budget.Schedule with robfig/cron (nodepool.go:318);
we implement the standard minute/hour/dom/month/dow grammar with lists,
ranges, and steps — enough for the budget use case without a dependency.
"""

from __future__ import annotations

import functools
import time


def _parse_field(spec: str, lo: int, hi: int, wrap: int | None = None) -> frozenset:
    """wrap: a value that aliases lo (Vixie cron allows dow 7 == Sunday)."""
    out = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part == "*" or part == "":
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            v = int(part)
            rng = range(v, v + 1)
        for x in rng:
            if (x - rng.start) % step:
                continue
            if x == wrap:
                x = lo
            if not lo <= x <= hi:
                raise ValueError(f"cron field value {x} out of range [{lo},{hi}] in {spec!r}")
            out.add(x)
    return frozenset(out)


class CronSchedule:
    def __init__(self, spec: str):
        spec = spec.strip()
        aliases = {
            "@hourly": "0 * * * *",
            "@daily": "0 0 * * *",
            "@midnight": "0 0 * * *",
            "@weekly": "0 0 * * 0",
            "@monthly": "0 0 1 * *",
            "@yearly": "0 0 1 1 *",
            "@annually": "0 0 1 1 *",
        }
        spec = aliases.get(spec, spec)
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"invalid cron spec {spec!r}")
        self.minutes = _parse_field(fields[0], 0, 59)
        self.hours = _parse_field(fields[1], 0, 23)
        self.dom = _parse_field(fields[2], 1, 31)
        self.months = _parse_field(fields[3], 1, 12)
        self.dow = _parse_field(fields[4], 0, 6, wrap=7)  # 0 (or 7) = Sunday
        self.dom_wild = fields[2] == "*"
        self.dow_wild = fields[4] == "*"

    def _matches(self, t: time.struct_time) -> bool:
        if t.tm_min not in self.minutes or t.tm_hour not in self.hours:
            return False
        if t.tm_mon not in self.months:
            return False
        dow = (t.tm_wday + 1) % 7  # python: Mon=0 → cron: Sun=0
        dom_ok = t.tm_mday in self.dom
        dow_ok = dow in self.dow
        # standard cron rule: if both dom and dow are restricted, OR them
        if not self.dom_wild and not self.dow_wild:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def prev(self, now: float, lookback_minutes: int = 366 * 24 * 60) -> float | None:
        """Most recent firing time <= now, or None within the lookback."""
        minute = int(now // 60) * 60
        for _ in range(lookback_minutes):
            if self._matches(time.gmtime(minute)):
                return float(minute)
            minute -= 60
        return None

    def next(self, now: float, lookahead_days: int = 366) -> float | None:
        minute = (int(now // 60) + 1) * 60
        for _ in range(lookahead_days * 24 * 60):
            if self._matches(time.gmtime(minute)):
                return float(minute)
            minute += 60
        return None


@functools.lru_cache(maxsize=512)
def parse_schedule(spec: str) -> CronSchedule:
    """Cached parse — Budget.is_active runs on every reconcile loop."""
    return CronSchedule(spec)
