"""Resource-list algebra.

Semantics follow the reference's pkg/utils/resources (resources.go:
RequestsForPods, Merge, Subtract, Fits:221, MaxResources:175, Cmp) but are
implemented on plain dict[str, float] resource lists, which also serve as the
row format for the device-side demand/allocatable tensors (ops/tensorize.py).
"""

from __future__ import annotations

from karpenter_tpu.utils.quantity import parse_quantity

# Canonical resource names (subset of k8s core; extended resources are open-ended)
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

ResourceList = dict  # dict[str, float]

_EPS = 1e-9
# relative slack for fit checks: byte-scale resources pass through float32
# device tensors whose ulp at 128Gi dwarfs any absolute epsilon. Shared by
# fits() and the solver's vectorized decode so the two paths cannot drift.
FIT_REL_EPS = 1e-6


def parse_resources(spec) -> ResourceList:
    """Parse {"cpu": "100m", "memory": "1Gi"} style specs into float lists."""
    if spec is None:
        return {}
    return {k: parse_quantity(v) for k, v in spec.items()}


def merge(*lists) -> ResourceList:
    """Element-wise sum across resource lists."""
    out: ResourceList = {}
    for rl in lists:
        if not rl:
            continue
        for k, v in rl.items():
            out[k] = out.get(k, 0.0) + v
    return out


def subtract(a: ResourceList, b: ResourceList) -> ResourceList:
    """a - b over the union of keys (may go negative, like the reference)."""
    out = dict(a or {})
    for k, v in (b or {}).items():
        out[k] = out.get(k, 0.0) - v
    return out


def max_resources(*lists) -> ResourceList:
    """Element-wise max — used for init-container request folding."""
    out: ResourceList = {}
    for rl in lists:
        for k, v in (rl or {}).items():
            if v > out.get(k, 0.0):
                out[k] = v
    return out


def fits(candidate: ResourceList, total: ResourceList) -> bool:
    """True iff every requested resource in candidate is available in total.

    A resource absent from total counts as zero capacity (so any positive
    request for it fails), matching resources.go:221. The tolerance is
    relative: byte-scale resources (memory) pass through float32 device
    tensors, whose ulp at 128Gi dwarfs any absolute epsilon.
    """
    for k, v in (candidate or {}).items():
        cap = total.get(k, 0.0)
        if v > cap + _EPS + FIT_REL_EPS * abs(cap):
            return False
    return True


def any_negative(rl: ResourceList) -> bool:
    return any(v < -_EPS for v in (rl or {}).values())


def exceeds(candidate: ResourceList, limits: ResourceList) -> list[str]:
    """Resource names in candidate exceeding limits; keys absent from limits
    are unconstrained (NodePool.Limits semantics, nodepool_status.go)."""
    out = []
    for k, lim in (limits or {}).items():
        if (candidate or {}).get(k, 0.0) > lim + _EPS:
            out.append(k)
    return out


def is_zero(rl: ResourceList) -> bool:
    return all(abs(v) <= _EPS for v in (rl or {}).values())


def pod_requests(pod) -> ResourceList:
    """Effective scheduling requests for a pod.

    Mirrors the kube-scheduler rule the reference relies on
    (pkg/utils/resources RequestsForPods): max(sum(containers),
    max(initContainers)) + pod overhead, plus an implicit "pods": 1.
    """
    container_sum = merge(*[c.get("requests", {}) for c in getattr(pod, "containers", None) or []])
    init_max = max_resources(*[c.get("requests", {}) for c in getattr(pod, "init_containers", None) or []])
    base = getattr(pod, "requests", None) or {}
    out = merge(max_resources(container_sum, init_max), base, getattr(pod, "overhead", None) or {})
    out[PODS] = 1.0
    return out
