"""Pod phase/ownership predicates (reference pkg/utils/pod/scheduling.go)."""

from __future__ import annotations


def is_scheduled(pod) -> bool:
    return bool(pod.node_name)


def is_terminal(pod) -> bool:
    return pod.phase in ("Succeeded", "Failed")


def is_terminating(pod) -> bool:
    return pod.metadata.deletion_timestamp is not None or pod.terminating


def is_owned_by_daemonset(pod) -> bool:
    return pod.owned_by_daemonset()


def is_owned_by_node(pod) -> bool:
    return any(o.get("kind") == "Node" for o in pod.metadata.owner_references)


def failed_to_schedule(pod) -> bool:
    return any(
        c.get("type") == "PodScheduled"
        and c.get("status") == "False"
        and c.get("reason") == "Unschedulable"
        for c in pod.conditions
    )


def is_provisionable(pod) -> bool:
    """scheduling.go IsProvisionable:82 — a pending pod karpenter should act
    on: marked unschedulable by the scheduler, not daemonset/static."""
    return (
        not is_scheduled(pod)
        and not is_terminal(pod)
        and not is_terminating(pod)
        and failed_to_schedule(pod)
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
    )


def is_reschedulable(pod) -> bool:
    """scheduling.go IsReschedulable:42 — counts toward capacity we must
    recreate when disrupting its node. Daemonset pods are excluded: the
    daemonset controller recreates them on the replacement node, and their
    requests are already reserved as daemon overhead."""
    return (
        not is_terminal(pod)
        and not is_terminating(pod)
        and not is_owned_by_node(pod)
        and not is_owned_by_daemonset(pod)
    )


def is_evictable(pod) -> bool:
    """scheduling.go IsEvictable:55 — the drain path should evict it."""
    return not is_terminal(pod) and not is_terminating(pod)


def is_waiting_eviction(pod) -> bool:
    return not is_terminal(pod) and not is_terminating(pod)
