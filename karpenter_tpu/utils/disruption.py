"""Disruption cost model.

Mirror of the reference's utils/disruption/disruption.go:37-78: a node's
disruption cost is the sum over its reschedulable pods of the pod's
eviction cost (priority-derived) scaled by the node's remaining lifetime
fraction — nodes close to expiry are cheap to disrupt.
"""

from __future__ import annotations

EVICTION_COST_ANNOTATION = "cluster-autoscaler.kubernetes.io/pod-eviction-cost"


def pod_eviction_cost(pod) -> float:
    """disruption.go GetPodEvictionCost: 1 + priority/1e6, overridden by the
    eviction-cost annotation, clamped to [-1e6, 1e6]."""
    cost = 1.0
    priority = pod.priority or 0
    cost += priority / 1e6
    raw = pod.metadata.annotations.get(EVICTION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost = float(raw)
        except ValueError:
            pass
    return min(max(cost, -1e6), 1e6)


def lifetime_remaining(state_node, expire_after: float | None, now: float) -> float:
    """Fraction of the node's lifetime left (disruption.go
    LifetimeRemaining): 1.0 when expiry is disabled."""
    if not expire_after:
        return 1.0
    node = state_node.node
    created = (
        node.metadata.creation_timestamp
        if node is not None
        else (
            state_node.node_claim.metadata.creation_timestamp
            if state_node.node_claim is not None
            else now
        )
    )
    remaining = 1.0 - (now - created) / expire_after
    return min(max(remaining, 0.0), 1.0)


def disruption_cost(pods, *, state_node=None, expire_after=None, now=0.0) -> float:
    cost = sum(pod_eviction_cost(p) for p in pods)
    if state_node is not None:
        cost *= lifetime_remaining(state_node, expire_after, now)
    return cost
