"""Kubernetes resource-quantity parsing and formatting.

Replaces the reference's dependence on k8s.io/apimachinery resource.Quantity
(used throughout pkg/utils/resources). Internally every quantity is a float:
cpu in cores, memory/storage in bytes, counts as plain numbers. Parsing
accepts the k8s grammar: decimal ("1.5"), milli ("1500m"), binary suffixes
("1Gi"), and decimal suffixes ("1G").
"""

from __future__ import annotations

_BINARY = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DECIMAL = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}


def parse_quantity(value) -> float:
    """Parse a k8s quantity string (or passthrough number) to a float."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        return 0.0
    for suffix, mult in _BINARY.items():
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    # longest decimal suffixes are single-char; check last char
    last = s[-1]
    if last in _DECIMAL:
        return float(s[:-1]) * _DECIMAL[last]
    return float(s)


def format_quantity(value: float, resource: str = "") -> str:
    """Human-readable formatting; memory-like resources in binary units."""
    if resource in ("memory", "ephemeral-storage") and value >= 2**20:
        for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
            mult = _BINARY[suffix]
            if value >= mult and abs(value / mult - round(value / mult, 3)) < 1e-9:
                return f"{round(value / mult, 3):g}{suffix}"
    if resource == "cpu" and 0 < value < 10 and abs(value * 1000 - round(value * 1000)) < 1e-9:
        m = round(value * 1000)
        if m % 1000:
            return f"{m}m"
    return f"{value:g}"
