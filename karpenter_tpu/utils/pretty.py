"""ChangeMonitor: emit-on-change gate for noisy log/event sites.

Behavioral mirror of the reference's pkg/utils/pretty/change_monitor.go:
callers ask ``has_changed(key, value)`` before logging; the answer is True
only when the key is new, the value differs from the last one seen for that
key, or the entry has outlived its TTL. Unlike the event recorder's 90 s
exact-message dedupe (operator/events.py), this suppresses *stable* states
indefinitely (up to the TTL) while letting any CHANGE through immediately —
the right shape for per-pod FailedScheduling chatter, where the same
unschedulable pod re-reports every batch.
"""

from __future__ import annotations

DEFAULT_TTL = 24 * 3600.0  # change_monitor.go: 24h


class ChangeMonitor:
    def __init__(self, ttl: float = DEFAULT_TTL, clock=None):
        from karpenter_tpu.utils.clock import Clock

        self.ttl = ttl
        self.clock = clock or Clock()
        self._seen: dict = {}  # key -> (expiry, value hash)

    def has_changed(self, key, value) -> bool:
        """True iff `value` for `key` is new/changed/expired; records it."""
        now = self.clock.now()
        h = hash(repr(value))
        cached = self._seen.get(key)
        if cached is not None and cached[0] > now and cached[1] == h:
            return False
        if len(self._seen) > 8192:  # expired entries drain lazily
            self._seen = {k: v for k, v in self._seen.items() if v[0] > now}
        self._seen[key] = (now + self.ttl, h)
        return True

    def forget(self, key):
        self._seen.pop(key, None)
