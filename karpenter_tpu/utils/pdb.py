"""PodDisruptionBudget limits: can a pod be evicted right now?

Mirror of the reference's utils/pdb.Limits (limits.go:35-94): collect all
PDBs, map each pod to the PDBs selecting it, and report the first PDB that
currently allows zero disruptions. The disruption controller uses this to
exclude candidates whose drain would block (types.go:64).
"""

from __future__ import annotations


class PdbLimits:
    def __init__(self, store):
        self._pdbs = []  # [(pdb, disruptions_allowed)]
        for pdb in store.list("pdbs"):
            self._pdbs.append((pdb, store._disruptions_allowed(pdb)))

    def can_evict(self, pod) -> str | None:
        """Returns the name of a blocking PDB, or None if evictable."""
        for pdb, allowed in self._pdbs:
            if pdb.metadata.namespace != pod.metadata.namespace:
                continue
            if pdb.selector is not None and pdb.selector.matches(pod.metadata.labels):
                if allowed <= 0:
                    return pdb.metadata.name
        return None
