"""The ONE env-knob accessor surface (int/float/bool/str + apply).

Originally grown in service/session.py so the service plane's knobs could
not drift in empty-string/garbage/clamp behavior; hoisted here when the
decision ledger (obs/decisions.py) needed the same semantics from a layer
that must not import the service plane (service → models → obs would
cycle). service/session.py re-exports these names, so every existing
importer keeps working.

This module is the ONLY place in the package allowed to touch
``os.environ`` directly: graftlint's GL501 (analysis/contracts.py) flags
any read elsewhere, so a new knob cannot bypass the shared parse/clamp
semantics — or escape the cache-fingerprint coverage check — by going
straight to the environment. ``env_str`` is the raw accessor for string/
enum/tri-state knobs whose call sites keep their own value tests;
``applied_env`` is the save/apply/restore half (the replay capsule
re-applies captured knobs around offline replays through it).
"""

from __future__ import annotations

import os

__all__ = ["env_int", "env_float", "env_bool", "env_str", "snapshot",
           "applied_env"]


def env_int(name: str, default: int, minimum: int | None = None) -> int:
    """Empty or unparseable falls back to `default`; `minimum` clamps the
    floor."""
    try:
        v = int(os.environ.get(name, "") or default)
    except ValueError:
        v = default
    return v if minimum is None else max(v, minimum)


def env_float(name: str, default: float,
              minimum: float | None = None) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        v = default
    return v if minimum is None else max(v, minimum)


def env_bool(name: str, default: bool) -> bool:
    """Unset/empty falls back to `default`; 0/false/off/no (any case)
    disable, anything else enables."""
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off", "no")


def env_str(name: str, default: str | None = None) -> str | None:
    """Raw passthrough: the knob's exact string, or ``default`` when
    unset. For enum/tri-state/path knobs whose call sites own the value
    test (KARPENTER_PALLAS's exact-"1" opt-in, the ASSUME_ACCELERATOR
    tri-state, TRACE_DIR/PROFILE_DIR paths) — the point is routing the
    READ through this module, not normalizing the value."""
    # graftlint: disable=GL103 -- freeze-at-trace is the documented contract
    # of the one jit-reachable caller (kernels.pallas_enabled, which carries
    # its own GL103 justification): callers caching jitted wrappers resolve
    # the knob HOST-side and key their cache on it
    return os.environ.get(name, default)


class applied_env:
    """Temporarily apply ``mapping``'s values for ``names`` (a name absent
    from the mapping is UNSET, not left alone), restoring the previous
    environment on exit. The replay capsule (obs/capsule.py) rides this to
    reproduce capture-time routing/partition knobs around an offline
    replay; tests use it for knob pinning without os.environ surgery."""

    def __init__(self, mapping: dict, names):
        self._names = tuple(names)
        self._mapping = dict(mapping or {})
        self._saved: dict = {}

    def __enter__(self):
        for n in self._names:
            self._saved[n] = os.environ.get(n)
            if n in self._mapping:
                os.environ[n] = self._mapping[n]
            else:
                os.environ.pop(n, None)
        return self

    def __exit__(self, et, ev, tb):
        for n, v in self._saved.items():
            if v is None:
                os.environ.pop(n, None)
            else:
                os.environ[n] = v
        return False


def snapshot(prefix: str = "KARPENTER_") -> dict:
    """Every set env knob under ``prefix`` — the replay capsule's
    environment record (obs/capsule.py): a capture's routing/partition/
    repair knobs ride along so an offline replay can reproduce the exact
    ladder decisions the capturing process made."""
    return {k: v for k, v in os.environ.items() if k.startswith(prefix)}
