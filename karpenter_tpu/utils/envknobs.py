"""The ONE env-knob parser trio (int/float/bool).

Originally grown in service/session.py so the service plane's knobs could
not drift in empty-string/garbage/clamp behavior; hoisted here when the
decision ledger (obs/decisions.py) needed the same semantics from a layer
that must not import the service plane (service → models → obs would
cycle). service/session.py re-exports these names, so every existing
importer keeps working.
"""

from __future__ import annotations

import os

__all__ = ["env_int", "env_float", "env_bool", "snapshot"]


def env_int(name: str, default: int, minimum: int | None = None) -> int:
    """Empty or unparseable falls back to `default`; `minimum` clamps the
    floor."""
    try:
        v = int(os.environ.get(name, "") or default)
    except ValueError:
        v = default
    return v if minimum is None else max(v, minimum)


def env_float(name: str, default: float,
              minimum: float | None = None) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        v = default
    return v if minimum is None else max(v, minimum)


def env_bool(name: str, default: bool) -> bool:
    """Unset/empty falls back to `default`; 0/false/off/no (any case)
    disable, anything else enables."""
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off", "no")


def snapshot(prefix: str = "KARPENTER_") -> dict:
    """Every set env knob under ``prefix`` — the replay capsule's
    environment record (obs/capsule.py): a capture's routing/partition/
    repair knobs ride along so an offline replay can reproduce the exact
    ladder decisions the capturing process made."""
    return {k: v for k, v in os.environ.items() if k.startswith(prefix)}
