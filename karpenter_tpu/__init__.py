"""karpenter-tpu: a TPU-native cluster-capacity framework.

A brand-new implementation of the capabilities of Karpenter core
(sigs.k8s.io/karpenter, surveyed in SURVEY.md): watching unschedulable pods,
simulating kube-scheduler constraints, bin-packing pods onto priced instance
types, launching right-sized nodes, and continuously consolidating the
cluster under disruption budgets.

The two combinatorial hot paths of the reference — the provisioning
bin-packer (pkg/controllers/provisioning/scheduling/scheduler.go:195) and the
consolidation search (pkg/controllers/disruption) — are reformulated here as
batched pod-group x instance-type feasibility tensors with a greedy/LP-relaxed
assignment kernel in JAX/XLA, sharded via shard_map over a device mesh, with
an in-process FFD fallback when no accelerator is available.

Layering (mirrors SURVEY.md §1, re-architected TPU-first):

    api/            L0  data model (NodePool, NodeClaim, Pod, Node, labels)
    scheduling/     L1  constraint algebra (Requirements, Taints, ports, volumes)
    cloudprovider/  L2  cloud-provider SPI + fake + kwok catalog
    state/          L3  in-memory cluster mirror + tensor snapshots
    ops/            --  tensorization compilers + device kernels
    models/         --  Solver implementations (FFD host, TPU batched)
    parallel/       --  mesh / shard_map sharded solve
    controllers/    L4-L6 provisioning, disruption, lifecycle
    kube/           --  in-memory apiserver (envtest/kwok analog)
    operator/       L7  options, runtime wiring
"""

__version__ = "0.1.0"
