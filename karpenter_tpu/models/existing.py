"""ExistingNode: scheduling simulation against real (or in-flight) capacity.

Behavioral mirror of the reference's scheduling ExistingNode
(pkg/controllers/provisioning/scheduling/existingnode.go:40-120): wraps a
StateNode snapshot with the same admission pipeline as an in-flight claim —
taints → host ports → volume limits → requirement compatibility → topology
tightening → resource fit against the node's cached availability. Unlike a
claim, requirements come from the node's actual labels, so compatibility is
strict (no undefined-well-known-label allowance).
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.scheduling import (
    IN,
    Requirement,
    Requirements,
    Taints,
    has_preferred_node_affinity,
    label_requirements,
    pod_requirements,
    strict_pod_requirements,
)
from karpenter_tpu.utils import resources as resutil


class ExistingNode:
    def __init__(self, state_node, topology, daemon_resources: dict | None = None, kube=None):
        self.state_node = state_node
        self.topology = topology
        self.kube = kube
        self.pods: list = []  # newly scheduled this solve
        # daemonsets that have not yet landed on this node still reserve
        # their requests (existingnode.go:44-56, clamped at zero)
        remaining_daemons = resutil.subtract(
            daemon_resources or {}, state_node.daemonset_requests()
        )
        self.requests = {r: max(v, 0.0) for r, v in remaining_daemons.items()}
        self.cached_available = state_node.available()
        self.taints = Taints(state_node.taints())
        self.requirements = label_requirements(state_node.labels())
        self.requirements.add(Requirement(wk.HOSTNAME_LABEL, IN, [state_node.hostname]))
        topology.register(wk.HOSTNAME_LABEL, state_node.hostname)
        self.host_ports = state_node.host_port_usage
        self.volumes = state_node.volume_usage

    def fork(self, topology) -> "ExistingNode":
        """Cheap per-simulation copy of a prototype built at the same
        cluster-state generation: shares everything `add` never mutates in
        place (the taint set, the initial requirements — `add` REPLACES
        self.requirements with a fresh object rather than mutating — and
        the availability dicts) and copies what it does (usage trackers,
        the requests dict, the placed-pod list). Lets one disruption
        round's tensorized bundle serve every confirming simulation
        without re-running the O(E) ExistingNode constructor per solve."""
        out = object.__new__(ExistingNode)
        out.state_node = self.state_node
        out.topology = topology
        out.kube = self.kube
        out.pods = []
        out.requests = dict(self.requests)
        out.cached_available = self.cached_available
        out.taints = self.taints
        out.requirements = self.requirements
        out.host_ports = self.host_ports.copy()
        out.volumes = self.volumes.copy()
        topology.register(wk.HOSTNAME_LABEL, self.state_node.hostname)
        return out

    @property
    def name(self) -> str:
        return self.state_node.name

    @property
    def scheduled_pods(self) -> list:
        return self.pods

    def add(self, pod) -> str | None:
        """Try to place pod on this node; mutates only on success
        (existingnode.go Add:64)."""
        err = self.taints.tolerates(pod)
        if err:
            return err
        err = self.host_ports.conflicts(pod)
        if err:
            return f"checking host port usage, {err}"
        volume_limits = self._volume_limits()
        if volume_limits:
            err = self.volumes.exceeds(pod, volume_limits, kube=self.kube)
            if err:
                return f"checking volume usage, {err}"

        node_reqs = Requirements(*self.requirements.values())
        pod_reqs = pod_requirements(pod)
        strict = strict_pod_requirements(pod) if has_preferred_node_affinity(pod) else pod_reqs
        err = node_reqs.compatible(strict)
        if err:
            return f"incompatible requirements, {err}"
        node_reqs.add(*strict.values())

        topo_reqs, err = self.topology.add_requirements(strict, node_reqs, pod)
        if err:
            return err
        err = node_reqs.compatible(topo_reqs)
        if err:
            return err
        node_reqs.add(*topo_reqs.values())

        requests = resutil.merge(self.requests, pod.effective_requests())
        if not resutil.fits(requests, self.cached_available):
            return "exceeds node resources"

        self.pods.append(pod)
        self.requests = requests
        self.requirements = node_reqs
        self.topology.record(pod, node_reqs)
        self.host_ports.add(pod)
        if volume_limits:
            self.volumes.add(pod, kube=self.kube)
        return None

    def _volume_limits(self) -> dict:
        """Per-CSI-driver attachable volume limits advertised by the node
        (the reference resolves these from CSINode objects)."""
        node = self.state_node.node
        if node is None:
            return {}
        return getattr(node, "volume_limits", None) or {}

    def __repr__(self):
        return f"ExistingNode({self.name}, +pods={len(self.pods)})"
