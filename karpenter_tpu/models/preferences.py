"""Progressive soft-constraint relaxation.

Behavioral mirror of the reference's Preferences.Relax
(pkg/controllers/provisioning/scheduling/preferences.go:38-147): each call
applies exactly ONE relaxation, trying in order — drop a required
node-affinity OR-alternative, drop the heaviest preferred pod-affinity /
pod-anti-affinity / node-affinity term, drop a ScheduleAnyway topology
spread, and (when enabled) tolerate PreferNoSchedule taints.
"""

from __future__ import annotations

from karpenter_tpu.api.objects import Toleration, sort_terms_by_weight


class Preferences:
    def __init__(self, tolerate_prefer_no_schedule: bool = False):
        self.tolerate_prefer_no_schedule = tolerate_prefer_no_schedule

    def relax(self, pod) -> bool:
        relaxations = [
            self._remove_required_node_affinity_term,
            self._remove_preferred_pod_affinity_term,
            self._remove_preferred_pod_anti_affinity_term,
            self._remove_preferred_node_affinity_term,
            self._remove_topology_spread_schedule_anyway,
        ]
        if self.tolerate_prefer_no_schedule:
            relaxations.append(self._tolerate_prefer_no_schedule_taints)
        for fn in relaxations:
            if fn(pod):
                return True
        return False

    @staticmethod
    def _remove_required_node_affinity_term(pod) -> bool:
        na = pod.affinity.node_affinity if pod.affinity else None
        # OR-alternatives: drop the first term so the next is tried; the last
        # term can never be removed
        if na and len(na.required) > 1:
            na.required = na.required[1:]
            return True
        return False

    @staticmethod
    def _remove_preferred_pod_affinity_term(pod) -> bool:
        pa = pod.affinity.pod_affinity if pod.affinity else None
        if pa and pa.preferred:
            pa.preferred = sort_terms_by_weight(pa.preferred)[1:]
            return True
        return False

    @staticmethod
    def _remove_preferred_pod_anti_affinity_term(pod) -> bool:
        pa = pod.affinity.pod_anti_affinity if pod.affinity else None
        if pa and pa.preferred:
            pa.preferred = sort_terms_by_weight(pa.preferred)[1:]
            return True
        return False

    @staticmethod
    def _remove_preferred_node_affinity_term(pod) -> bool:
        na = pod.affinity.node_affinity if pod.affinity else None
        if na and na.preferred:
            na.preferred = sort_terms_by_weight(na.preferred)[1:]
            return True
        return False

    @staticmethod
    def _remove_topology_spread_schedule_anyway(pod) -> bool:
        for i, tsc in enumerate(pod.topology_spread_constraints):
            if tsc.when_unsatisfiable == "ScheduleAnyway":
                pod.topology_spread_constraints = (
                    pod.topology_spread_constraints[:i] + pod.topology_spread_constraints[i + 1 :]
                )
                return True
        return False

    @staticmethod
    def _tolerate_prefer_no_schedule_taints(pod) -> bool:
        tol = Toleration(operator="Exists", effect="PreferNoSchedule")
        if any(
            t.key == tol.key and t.operator == tol.operator and t.effect == tol.effect
            for t in pod.tolerations
        ):
            return False
        pod.tolerations = list(pod.tolerations) + [tol]
        return True
