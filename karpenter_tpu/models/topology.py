"""Topology constraint engine: spread, pod affinity, pod anti-affinity.

Behavioral mirror of the reference's pkg/controllers/provisioning/scheduling/
{topology.go:43-309, topologygroup.go:56-274, topologynodefilter.go}:

- `TopologyGroup` tracks per-(key,type,selector) domain→count maps, hashed
  and deduplicated so one group serves N owner pods (topologygroup.go Hash).
- Anti-affinity is tracked BOTH ways: `inverse` groups follow pods that
  declare anti-affinity so that pods they select can be kept away
  (topology.go:49-53).
- `next domain` math mirrors kube-scheduler: spread picks the least-loaded
  allowed domain within maxSkew (topologygroup.go:167-217), affinity requires
  a non-empty domain (:219), anti-affinity an empty one (:252).

The device path (ops/waves.py) compiles the self-selecting common cases of
these groups into per-zone sub-groups / per-bin caps; everything else runs
through this host engine.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.scheduling import (
    DOES_NOT_EXIST,
    IN,
    Requirement,
    Requirements,
    label_requirements,
    node_selector_requirements,
)

TYPE_SPREAD = "topology spread"
TYPE_AFFINITY = "pod affinity"
TYPE_ANTI_AFFINITY = "pod anti-affinity"

_MAX = 1 << 31


def has_pod_anti_affinity(pod) -> bool:
    return bool(
        pod.affinity
        and pod.affinity.pod_anti_affinity
        and (pod.affinity.pod_anti_affinity.required or pod.affinity.pod_anti_affinity.preferred)
    )


def ignored_for_topology(pod) -> bool:
    """topology.go IgnoredForTopology:437 — unscheduled/terminal/terminating
    pods don't count."""
    return not pod.node_name or pod.phase in ("Succeeded", "Failed") or pod.terminating


class TopologyNodeFilter:
    """OR of requirement sets a node must match to count for a spread group
    (topologynodefilter.go)."""

    def __init__(self, terms):
        self.terms = terms  # [Requirements]; empty = always matches

    @classmethod
    def for_pod(cls, pod):
        selector_reqs = label_requirements(pod.node_selector)
        na = pod.affinity.node_affinity if pod.affinity else None
        if na is None or not na.required:
            return cls([selector_reqs])
        terms = []
        for term in na.required:
            reqs = Requirements()
            reqs.add(*selector_reqs.values())
            reqs.add(*node_selector_requirements(term.match_expressions).values())
            terms.append(reqs)
        return cls(terms)

    @classmethod
    def always(cls):
        return cls([])

    def matches_labels(self, labels: dict) -> bool:
        return self.matches_requirements(label_requirements(labels))

    def matches_requirements(self, reqs: Requirements) -> bool:
        if not self.terms:
            return True
        return any(
            reqs.compatible(t, allow_undefined=wk.WELL_KNOWN_LABELS) is None for t in self.terms
        )

    def hash_key(self):
        return tuple(
            tuple(sorted((r.key, r.complement, tuple(sorted(r.values))) for r in t.values()))
            for t in self.terms
        )


class TopologyGroup:
    def __init__(
        self,
        group_type: str,
        key: str,
        pod,
        namespaces: frozenset,
        selector,  # LabelSelector | None
        max_skew: int,
        min_domains: int | None,
        domains,  # iterable of known domain names
    ):
        self.type = group_type
        self.key = key
        self.namespaces = frozenset(namespaces)
        self.selector = selector
        self.max_skew = max_skew
        self.min_domains = min_domains
        self.node_filter = (
            TopologyNodeFilter.for_pod(pod) if group_type == TYPE_SPREAD else TopologyNodeFilter.always()
        )
        self.domains = {d: 0 for d in domains or ()}
        self.empty_domains = set(domains or ())
        self.owners: set = set()

    # --- identity -------------------------------------------------------
    def hash_key(self):
        sel = None
        if self.selector is not None:
            sel = (
                tuple(sorted(self.selector.match_labels.items())),
                tuple(
                    (e.key, e.operator, tuple(sorted(e.values)))
                    for e in self.selector.match_expressions
                ),
            )
        return (
            self.type,
            self.key,
            self.namespaces,
            sel,
            self.max_skew,
            self.node_filter.hash_key(),
        )

    # --- counting -------------------------------------------------------
    def record(self, *domains):
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1
            self.empty_domains.discard(d)

    def record_n(self, domain, n: int):
        """record() with multiplicity — the device decoder commits a whole
        group of identical pods at once."""
        self.domains[domain] = self.domains.get(domain, 0) + n
        self.empty_domains.discard(domain)

    def register(self, *domains):
        for d in domains:
            if d not in self.domains:
                self.domains[d] = 0
                self.empty_domains.add(d)

    def selects(self, pod) -> bool:
        if pod.namespace not in self.namespaces:
            return False
        if self.selector is None:
            return False
        return self.selector.matches(pod.metadata.labels)

    def counts(self, pod, requirements: Requirements) -> bool:
        return self.selects(pod) and self.node_filter.matches_requirements(requirements)

    # --- next-domain math ----------------------------------------------
    def get(self, pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type == TYPE_SPREAD:
            return self._next_spread(pod, pod_domains, node_domains)
        if self.type == TYPE_AFFINITY:
            return self._next_affinity(pod, pod_domains, node_domains)
        return self._next_anti_affinity(pod_domains)

    def _domain_min_count(self, pod_domains: Requirement) -> int:
        # hostname topologies can always mint a fresh (empty) node
        if self.key == wk.HOSTNAME_LABEL:
            return 0
        lo = _MAX
        supported = 0
        for domain, count in self.domains.items():
            if pod_domains.has(domain):
                supported += 1
                lo = min(lo, count)
        if self.min_domains is not None and supported < self.min_domains:
            lo = 0
        return lo

    def _next_spread(self, pod, pod_domains, node_domains) -> Requirement:
        lo = self._domain_min_count(pod_domains)
        self_selecting = self.selects(pod)
        best, best_count = None, _MAX
        # deterministic tie-break by domain name (the reference picks an
        # arbitrary min-count domain; determinism aids reproducibility)
        for domain in sorted(self.domains):
            if not node_domains.has(domain):
                continue
            count = self.domains[domain]
            if self_selecting:
                count += 1
            if count - lo <= self.max_skew and count < best_count:
                best, best_count = domain, count
        if best is None:
            return Requirement(self.key, DOES_NOT_EXIST)
        return Requirement(self.key, IN, [best])

    def _next_affinity(self, pod, pod_domains, node_domains) -> Requirement:
        options = [d for d in self.domains if pod_domains.has(d) and self.domains[d] > 0]
        if not options and self.selects(pod):
            # self-affinity bootstrap: prefer a domain the node already allows
            intersected = pod_domains.intersection(node_domains)
            for domain in sorted(self.domains):
                if intersected.has(domain):
                    options.append(domain)
                    break
            if not options:
                for domain in sorted(self.domains):
                    if pod_domains.has(domain):
                        options.append(domain)
                        break
        if not options:
            return Requirement(self.key, DOES_NOT_EXIST)
        return Requirement(self.key, IN, options)

    def _next_anti_affinity(self, pod_domains) -> Requirement:
        options = [
            d for d in self.empty_domains if pod_domains.has(d) and self.domains.get(d, 0) == 0
        ]
        if not options:
            return Requirement(self.key, DOES_NOT_EXIST)
        return Requirement(self.key, IN, options)


class Topology:
    """Hash-deduped topology group registry + the AddRequirements/Record
    protocol the scheduler drives (topology.go:43)."""

    def __init__(self, cluster=None, domains: dict | None = None, pods=()):
        self.cluster = cluster  # optional ClusterView (state plane)
        self.domains = {k: set(v) for k, v in (domains or {}).items()}
        self.topologies: dict = {}
        self.inverse_topologies: dict = {}
        self.excluded_pods = {p.uid for p in pods}
        # (namespace, labels) -> [tg...] whose selector matches; selects()
        # is a pure function of those two, so pods sharing a label
        # signature share one registry scan (the record path is
        # per-(pod, tg) otherwise — the dominant cost of committing a
        # device solve). update() invalidates (it can add groups).
        self._sel_memo: dict = {}
        # uid -> [tg...] the pod currently owns: update() un-registers via
        # this index instead of sweeping every registry group per pod
        self._owner_tgs: dict = {}
        if cluster is not None:
            self._update_inverse_affinities()
        for p in pods:
            self.update(p)

    # -- lifecycle -------------------------------------------------------
    def update(self, pod):
        """(Re)register pod as owner of its topologies; called initially and
        after each relaxation (topology.go Update:105)."""
        self._sel_memo.clear()  # may add groups below
        for tg in self._owner_tgs.pop(pod.uid, ()):
            tg.owners.discard(pod.uid)

        if has_pod_anti_affinity(pod):
            self._update_inverse_anti_affinity(pod, None)

        owned = []
        for tg in self._new_for_topologies(pod) + self._new_for_affinities(pod):
            key = tg.hash_key()
            existing = self.topologies.get(key)
            if existing is None:
                self._count_domains(tg)
                self.topologies[key] = tg
                existing = tg
            existing.owners.add(pod.uid)
            owned.append(existing)
        if owned:
            self._owner_tgs[pod.uid] = owned
        return None

    def register(self, topology_key: str, domain: str):
        for tg in self.topologies.values():
            if tg.key == topology_key:
                tg.register(domain)
        for tg in self.inverse_topologies.values():
            if tg.key == topology_key:
                tg.register(domain)

    # -- scheduler protocol ---------------------------------------------
    def add_requirements(self, pod_requirements, node_requirements, pod, allow_undefined=None):
        """Tighten node requirements with the next allowed domain per
        matching group (topology.go AddRequirements:168). Returns
        (Requirements, error)."""
        requirements = Requirements()
        requirements.add(*node_requirements.values())
        for tg in self._matching_topologies(pod, node_requirements):
            pod_domains = pod_requirements.get_req(tg.key)
            node_domains = node_requirements.get_req(tg.key)
            domains = tg.get(pod, pod_domains, node_domains)
            if len(domains) == 0:
                return None, (
                    f"unsatisfiable topology constraint for {tg.type}, key={tg.key}"
                )
            requirements.add(domains)
        return requirements, None

    def record(self, pod, requirements: Requirements, allow_undefined=None):
        """Commit domain usage after a pod lands (topology.go Record:141)."""
        self.record_many(pod, requirements, 1)

    def _selecting(self, pod) -> list:
        """Registry groups whose selector matches this pod, memoized by
        (namespace, labels) — the pure inputs of TopologyGroup.selects."""
        key = (pod.namespace, tuple(sorted(pod.metadata.labels.items())))
        sel = self._sel_memo.get(key)
        if sel is None:
            sel = self._sel_memo[key] = [
                tg for tg in self.topologies.values() if tg.selects(pod)
            ]
        return sel

    def record_many(self, pod, requirements: Requirements, n: int):
        """record() with multiplicity: the device decoder lands a group of
        n identical pods in one commit; `pod` is the group representative."""
        for tg in self._selecting(pod):
            if tg.node_filter.matches_requirements(requirements):
                domains = requirements.get_req(tg.key)
                if tg.type == TYPE_ANTI_AFFINITY:
                    for v in domains.values:
                        tg.record_n(v, n)
                elif len(domains) == 1:
                    tg.record_n(next(iter(domains.values)), n)
        for tg in self.inverse_topologies.values():
            if pod.uid in tg.owners:
                for v in requirements.get_req(tg.key).values:
                    tg.record_n(v, n)

    # -- construction helpers -------------------------------------------
    def _new_for_topologies(self, pod):
        out = []
        for cs in pod.topology_spread_constraints:
            out.append(
                TopologyGroup(
                    TYPE_SPREAD,
                    cs.topology_key,
                    pod,
                    frozenset({pod.namespace}),
                    cs.label_selector,
                    cs.max_skew,
                    cs.min_domains,
                    self.domains.get(cs.topology_key, ()),
                )
            )
        return out

    def _new_for_affinities(self, pod):
        out = []
        aff = pod.affinity
        if aff is None:
            return out
        for group_type, pa in ((TYPE_AFFINITY, aff.pod_affinity), (TYPE_ANTI_AFFINITY, aff.pod_anti_affinity)):
            if pa is None:
                continue
            terms = list(pa.required) + [w.pod_affinity_term for w in pa.preferred]
            for term in terms:
                out.append(
                    TopologyGroup(
                        group_type,
                        term.topology_key,
                        pod,
                        self._namespaces(pod.namespace, term),
                        term.label_selector,
                        _MAX,
                        None,
                        self.domains.get(term.topology_key, ()),
                    )
                )
        return out

    def _namespaces(self, pod_namespace, term) -> frozenset:
        if not term.namespaces and term.namespace_selector is None:
            return frozenset({pod_namespace})
        out = set(term.namespaces)
        if term.namespace_selector is not None and self.cluster is not None:
            out.update(self.cluster.namespaces_matching(term.namespace_selector))
        return frozenset(out)

    def _update_inverse_affinities(self):
        for pod, node_labels in self.cluster.pods_with_anti_affinity():
            if pod.uid in self.excluded_pods:
                continue
            self._update_inverse_anti_affinity(pod, node_labels)

    def _update_inverse_anti_affinity(self, pod, node_labels):
        """Track domains occupied by pods DECLARING anti-affinity so pods
        they select avoid them (topology.go:225). Preferences intentionally
        untracked."""
        for term in pod.affinity.pod_anti_affinity.required:
            tg = TopologyGroup(
                TYPE_ANTI_AFFINITY,
                term.topology_key,
                pod,
                self._namespaces(pod.namespace, term),
                term.label_selector,
                _MAX,
                None,
                self.domains.get(term.topology_key, ()),
            )
            key = tg.hash_key()
            existing = self.inverse_topologies.get(key)
            if existing is None:
                self.inverse_topologies[key] = tg
                existing = tg
            if node_labels and tg.key in node_labels:
                existing.record(node_labels[tg.key])
            existing.owners.add(pod.uid)

    def _count_domains(self, tg: TopologyGroup):
        """Seed group counts from existing cluster pods
        (topology.go countDomains:256)."""
        if self.cluster is None:
            return
        for pod, node_labels in self.cluster.pods_matching(tg.namespaces, tg.selector):
            if ignored_for_topology(pod) or pod.uid in self.excluded_pods:
                continue
            domain = (node_labels or {}).get(tg.key)
            if domain is None and tg.key == wk.HOSTNAME_LABEL:
                domain = pod.node_name
            if domain is None:
                continue
            if not tg.node_filter.matches_labels(node_labels or {}):
                continue
            tg.record(domain)

    def _matching_topologies(self, pod, requirements):
        out = [tg for tg in self.topologies.values() if pod.uid in tg.owners]
        out += [tg for tg in self.inverse_topologies.values() if tg.counts(pod, requirements)]
        return out

    @property
    def has_groups(self) -> bool:
        return bool(self.topologies or self.inverse_topologies)
