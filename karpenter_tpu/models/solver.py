"""Solver: the pluggable device/host boundary.

The reference has a single in-process Go loop; our build exposes a `Solver`
seam (the analog of the metrics-decorator precedent around CloudProvider,
SURVEY.md §2.3): `TPUSolver` compiles the snapshot to tensors and runs the
batched feasibility+pack kernels on the accelerator, then decodes bins back
into in-flight NodeClaims and validates them host-side; anything the device
path can't express (pod affinity, topology waves before M2, validation
failures, leftovers) flows through `HostSolver` — the faithful FFD loop —
seeded with the device-produced claims. Shapes are bucketed so XLA compiles
once per bucket.

Every kernel dispatch also records a replay capture (exact tensor inputs +
outputs, engine/rung, static params) onto the open round trace; anomalous
rounds serialize it as a replay capsule replayable bit-identically offline
— :mod:`karpenter_tpu.obs.capsule` and deploy/README.md "Replay capsules".

Bin-count estimation is additionally steered by an LP relaxation floor
(:mod:`karpenter_tpu.ops.relax` ``lp_bin_floor``, deploy/README.md
"LP relaxation rung"): a weak-duality certified lower bound on the bins any
integral packing needs, computed by the same device-resident PDHG kernel
family that serves the joint-consolidation rung. Solves the floor steered
record the ``relax`` rung on the ``solver.route`` ledger.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from karpenter_tpu import obs
from karpenter_tpu.obs import decisions, devplane
from karpenter_tpu.api import labels as wk
from karpenter_tpu.models.inflight import InFlightNodeClaim
from karpenter_tpu.models.scheduler import NullTopology, Scheduler, SchedulerResults
from karpenter_tpu.ops import tensorize
from karpenter_tpu.ops.tensorize import (
    SPREAD_OWNED_MIN,
    UNCAPPED,
    bucket as _bucket,
    device_eligible,
    kernel_args,
)
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.envknobs import env_bool, env_int, env_str


class Solver:
    def solve(self, pods, templates, instance_types, **kw) -> SchedulerResults:
        raise NotImplementedError


class HostSolver(Solver):
    """The reference algorithm (FFD loop) on the host. Fallback + oracle."""

    def solve(
        self,
        pods,
        templates,
        instance_types,
        topology=None,
        existing_nodes=(),
        daemon_overhead=None,
        limits=None,
        initial_claims=(),
        volume_topology=None,
        existing_base=None,  # tensor-derivation hint; the host loop has no tensors
    ) -> SchedulerResults:
        sched = Scheduler(
            templates,
            instance_types,
            topology=topology,
            existing_nodes=existing_nodes,
            daemon_overhead=daemon_overhead,
            remaining_resources=limits,
            volume_topology=volume_topology,
        )
        sched.new_claims = list(initial_claims)
        # the host FFD loop is one opaque leaf in the round's span tree:
        # grid regressions that route pods here show up as this span
        # dominating the trace (obs flight recorder)
        with obs.span("solve.host", pods=len(pods)):
            return sched.solve(pods)




# feasibility work (G*T*K*W mask cells) above which a multi-device mesh
# earns its collective overhead; single-chip installs never shard
SHARD_MIN_WORK = 1 << 21


def _make_packed(max_bins: int, use_pallas: bool, level_bits: int,
                 max_minv: int):
    """The traceable packed-kernel body: solve_step with every output
    flattened into ONE int32 buffer — shared by the plain jit wrapper
    (:func:`_packed_kernel`) and the coalescer's vmapped batch wrapper
    (:func:`_batched_solve_kernel`), so both compile the same program
    modulo the batch axis."""
    import jax.numpy as jnp

    from karpenter_tpu.ops import kernels

    def packed(args):
        out = kernels.solve_step(args, max_bins=max_bins, use_pallas=use_pallas,
                                 level_bits=level_bits, max_minv=max_minv)
        return jnp.concatenate([
            out["assign"].ravel(),
            out["assign_e"].ravel(),
            out["used"].astype(jnp.int32),
            out["tmpl"],
            out["F"].astype(jnp.int32).ravel(),
        ])

    return packed


def _packed_kernel(max_bins: int, use_pallas: bool = False, level_bits: int = 20,
                   max_minv: int = 0):
    """Jitted solve kernel with all outputs flattened into ONE int32
    buffer: over a tunneled chip every separate device->host array pays a
    full ~64ms round trip, which dominates these small tensors.

    Module-level cache: solver instances come and go (every Environment
    builds one), but the jit wrapper must be shared or each instance
    re-traces the scan — the dominant cost of a test suite with hundreds
    of environments."""
    cached = _PACKED_KERNELS.get((max_bins, use_pallas, level_bits, max_minv))
    if cached is not None:
        return cached

    import jax

    fn = jax.jit(_make_packed(max_bins, use_pallas, level_bits, max_minv))
    _PACKED_KERNELS[(max_bins, use_pallas, level_bits, max_minv)] = fn
    return fn


_PACKED_KERNELS: dict = {}


def _batched_solve_kernel(max_bins: int, level_bits: int = 20,
                          max_minv: int = 0):
    """jit(vmap(packed kernel)) over a stacked leading axis: the solver
    service's coalesced dispatch — N concurrent tenants' same-shape solves
    ride ONE device call and demux by row (the same vmap-over-snapshots
    shape the batched consolidation probe compiles, ops/consolidate.py
    ``_batched_kernel``). Static params thread statically for the same
    reason the probe's do: solve_step's host-side reads cannot run on a
    tracer."""
    key = (max_bins, level_bits, max_minv, "vmap")
    # graftlint: disable=GL501 -- "vmap" entries pin use_pallas=False, so
    # the pallas knob (reachable through solve_step) cannot affect them
    cached = _PACKED_KERNELS.get(key)
    if cached is not None:
        return cached

    import jax

    packed = _make_packed(max_bins, False, level_bits, max_minv)
    fn = jax.jit(jax.vmap(packed))
    _PACKED_KERNELS[key] = fn
    return fn


def batched_invoke(args_list, max_bins: int, level_bits: int = 20,
                   max_minv: int = 0):
    """Run N same-shape solve snapshots as one vmapped device dispatch;
    returns one host output dict per input, each identical in layout to
    ``TPUSolver._invoke``'s. Every dict in ``args_list`` must carry the
    same keys with the same shapes/dtypes (the coalescer's bucket key
    guarantees it); the padded batch rows repeat the last snapshot and are
    dropped before demux. The pow-2 batch-axis waste and the compiled
    family land in the device-plane telemetry (site/family
    ``service.batch``)."""
    n = len(args_list)
    Np = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
    base = args_list[0]
    stacked = {
        k: np.stack([a[k] for a in args_list]
                    + [args_list[-1][k]] * (Np - n))
        for k in base
    }
    devplane.record_padding("service.batch", n, Np)
    kfn = _batched_solve_kernel(max_bins, level_bits, max_minv)
    t0 = time.perf_counter()
    with obs.span("solve.kernel", kind="device", batch=n):
        flat = np.asarray(kfn(stacked))
    devplane.record_dispatch(
        "service.batch",
        (Np, max_bins, level_bits, max_minv,
         tuple(sorted((k, v.shape[1:]) for k, v in stacked.items()))),
        time.perf_counter() - t0)
    return [TPUSolver._unpack(flat[i], args_list[i], max_bins)
            for i in range(n)]


# pods-per-solve below which the C++ engine beats the accelerator: the
# tunneled chip pays a fixed ~64 ms round trip per dispatch while the native
# engine finishes small instances in single-digit ms (measured on the grid:
# native grid-100 ≈ 5 ms vs 100+ ms through the tunnel). Override with
# KARPENTER_NATIVE_CUTOFF (0 disables ALL engine routing).
NATIVE_CUTOFF_PODS = 192


def _native_cutoff() -> int:
    """The routing master switch: 0 disables ALL engine routing (tests pin
    this to keep the XLA path under test)."""
    return env_int("KARPENTER_NATIVE_CUTOFF", NATIVE_CUTOFF_PODS)


def _exact_skip_enabled() -> bool:
    """KARPENTER_DECODE_EXACT_SKIP: the decoder's multi-group exact-skip
    A/B kill switch (resolved per call — decode is host-side)."""
    return env_bool("KARPENTER_DECODE_EXACT_SKIP", True)


# memoized: is the jax "device" an actual accelerator? On an install whose
# default backend is plain CPU the XLA path is an emulation of the device
# kernel — it pays trace/compile and a bin-sequential scan with none of the
# accelerator's parallelism, and the C++ engine beats it at EVERY size
# (measured: grid-5000 27s XLA-CPU vs 1.5s native on the same host). Real
# accelerator backends (tpu/axon/gpu) keep the device path.
_ACCEL_BACKEND: bool | None = None


def _accelerated_backend() -> bool:
    # KARPENTER_ASSUME_ACCELERATOR overrides the probe (1/0): tests use it
    # to pin the work-gate contract on CPU-only boxes, operators can use it
    # to force either stance when the backend probe misleads
    v = env_str("KARPENTER_ASSUME_ACCELERATOR")
    if v is not None:
        return v.strip().lower() in ("1", "true", "yes", "on")
    global _ACCEL_BACKEND
    if _ACCEL_BACKEND is None:
        try:
            import jax

            _ACCEL_BACKEND = jax.default_backend() != "cpu"
        except Exception:
            _ACCEL_BACKEND = False
    return _ACCEL_BACKEND
# batches at or below this many pods skip tensorization entirely and run
# the pure-Python FFD loop (the oracle): at single-pod scale even the C++
# engine's tensorize/decode overhead loses to walking the list directly
# (measured grid-1: 1.7 ms host vs ~4-7 ms native incl. tensorize).
# Gated by the same master switch (KARPENTER_NATIVE_CUTOFF=0 disables all
# routing); override with KARPENTER_HOST_CUTOFF.
HOST_CUTOFF_PODS = 8
# feasibility-work floor (real G×T cells, padding excluded) for the device:
# the kernel's advantage is parallelism over groups×types, so a batch with
# FEW DISTINCT GROUPS is a short sequential loop the C++ engine finishes in
# single-digit ms no matter how many pods ride each group (measured: 1k
# homogeneous pods × 10 types = 5 ms native vs 45 ms device; 10k pods ×
# 200 types with 8 signatures = 60 ms vs 135 ms). Override with
# KARPENTER_DEVICE_MIN_WORK (0 disables the work gate, leaving only the
# pods cutoff above).
DEVICE_MIN_WORK = 8192


class TPUSolver(Solver):
    def __init__(self):
        self.host = HostSolver()
        self.last_device_stats: dict = {}
        self._mesh = None
        self._mesh_checked = False
        # engine/route of the most recent kernel dispatch are THREAD-LOCAL:
        # the solver service drives one shared solver from concurrent gRPC
        # worker threads (the same reason mesh.LAST_RUN is thread-local),
        # and a tenant's replay capture stamped with another tenant's
        # engine would replay on the wrong rung. Within one solve() every
        # read follows its own thread's dispatch, so single-threaded
        # callers see no change.
        self._eng_tls = threading.local()

    @property
    def _last_engine(self) -> str:
        return getattr(self._eng_tls, "engine", "device")

    @_last_engine.setter
    def _last_engine(self, value: str):
        self._eng_tls.engine = value

    @property
    def _route(self):
        # (rung, reason) of the most recent kernel dispatch, recorded as
        # the solve's ONE "solver.route" decision-ledger verdict (rungs
        # mesh/native/xla/service/host — obs/decisions.py)
        return getattr(self._eng_tls, "route", None)

    @_route.setter
    def _route(self, value):
        self._eng_tls.route = value

    def _maybe_mesh(self):
        """The device mesh when >1 accelerator is attached (ICI on real
        hardware, virtual devices under xla_force_host_platform_device_count
        — parallel/mesh.py); None on single-chip installs."""
        if not self._mesh_checked:
            self._mesh_checked = True
            try:
                import jax

                if len(jax.devices()) > 1:
                    from karpenter_tpu.parallel import make_mesh

                    self._mesh = make_mesh()
            except Exception:
                self._mesh = None
        return self._mesh

    def _kernel(self, key):
        # the pallas toggle resolves HOST-side per call and keys the cache:
        # a trace-time env read would freeze the first solve's choice into
        # the module-lifetime jit wrapper
        from karpenter_tpu.ops.kernels import pallas_enabled

        return _packed_kernel(key[-3], pallas_enabled(), level_bits=key[-2],
                              max_minv=key[-1])

    def solve(
        self,
        pods,
        templates,
        instance_types,
        topology=None,
        existing_nodes=(),
        daemon_overhead=None,
        limits=None,
        max_bins: int | None = None,
        volume_topology=None,
        existing_base=None,
        tier_of=None,
    ) -> SchedulerResults:
        has_topology = bool(getattr(topology, "has_groups", topology is not None and not isinstance(topology, NullTopology)))
        host_cutoff = 0
        if _native_cutoff() > 0:
            host_cutoff = env_int("KARPENTER_HOST_CUTOFF", HOST_CUTOFF_PODS)
        if not templates or 0 < len(pods) <= host_cutoff:
            res = self.host.solve(
                pods,
                templates,
                instance_types,
                topology=topology,
                existing_nodes=existing_nodes,
                daemon_overhead=daemon_overhead,
                limits=limits,
                volume_topology=volume_topology,
            )
            # reset UNCONDITIONALLY: a stale last_device_stats from the
            # previous round would be re-read by the provisioner's
            # host-routed accounting and double-count its reasons
            reason = "small-batch" if templates else "no-templates"
            self.last_device_stats = dict(
                groups=0, types=0, device_pods=0, retry_pods=0,
                host_pods=len(pods), existing_pods=0, engine="host",
                host_routed={reason: len(pods)} if pods else {},
                cold_compiles=0, pad_waste_ratio=0.0,
            )
            decisions.record_decision("solver.route", "host", reason)
            return res
        existing_nodes = list(existing_nodes)
        # per-stage wall clock of this solve (waves compile / tensorize /
        # kernel dispatch / decode), surfaced through last_device_stats so
        # the perf harness can attribute grid wall clock to its stage
        from karpenter_tpu.ops.tensorize import STATS as _tz_stats

        stages: dict = {}
        _rows0 = (_tz_stats.get("group_row_hits", 0),
                  _tz_stats.get("group_row_misses", 0))
        # device-plane telemetry deltas for THIS solve: cold compiles paid
        # and pow-2 padding waste across its dispatches (perf surfaces
        # both per row; warm repeat rows must read 0 cold compiles)
        _dp0 = (devplane.STATS["cold_compiles"],
                devplane.STATS["pad_cells_actual"],
                devplane.STATS["pad_cells_padded"])

        # weight order decides which template a new bin opens from
        # (scheduler.go:267 tries templates in weight order)
        templates = sorted(templates, key=lambda t: (-t.weight, t.nodepool_name))

        if has_topology:
            # topology-constrained batch: the waves compiler turns the
            # self-selecting constraint shapes into zone-pinned subgroups /
            # per-bin caps; everything it can't express routes to the host
            from karpenter_tpu.ops import waves
            from karpenter_tpu.ops.tensorize import (
                device_basic_eligible,
                group_by_signature,
            )

            basic, rest = [], []
            for p in pods:
                ok = p.__dict__.get("_basic_elig_cache")
                if ok is None:
                    ok = device_basic_eligible(p)
                    p.__dict__["_basic_elig_cache"] = ok
                (basic if ok else rest).append(p)
            host_routed = {"ineligible-spec": len(rest)} if rest else {}
            t0 = time.perf_counter()
            plan = waves.compile_topology(group_by_signature(basic), topology)
            stages["waves_compile_ms"] = (time.perf_counter() - t0) * 1000.0
            rest.extend(plan.host_pods)
            for reason, n in getattr(plan, "host_reasons", {}).items():
                host_routed[reason] = host_routed.get(reason, 0) + n
            device_groups = plan.device_groups
            if not device_groups:
                self.last_device_stats = dict(
                    groups=0, types=0, device_pods=0, retry_pods=0,
                    host_pods=len(pods), existing_pods=0, engine="host",
                    host_routed=host_routed, cold_compiles=0,
                    pad_waste_ratio=0.0, **stages,
                )
                decisions.record_decision("solver.route", "host",
                                          "no-device-groups")
                return self.host.solve(
                    pods,
                    templates,
                    instance_types,
                    topology=topology,
                    existing_nodes=existing_nodes,
                    daemon_overhead=daemon_overhead,
                    limits=limits,
                    volume_topology=volume_topology,
                )
            eligible = [p for dg in device_groups for p in dg.pods]
            t0 = time.perf_counter()
            snap = tensorize(
                None,
                templates,
                instance_types,
                daemon_overhead=daemon_overhead,
                limits=limits,
                device_plan=plan,
            )
            stages["tensorize_ms"] = (time.perf_counter() - t0) * 1000.0
            device_plan = plan
        else:
            eligible, rest = [], []
            for p in pods:
                ok = p.__dict__.get("_elig_cache")
                if ok is None:
                    ok = device_eligible(p)
                    p.__dict__["_elig_cache"] = ok
                (eligible if ok else rest).append(p)
            host_routed = {"ineligible-spec": len(rest)} if rest else {}
            if not eligible:
                self.last_device_stats = dict(
                    groups=0, types=0, device_pods=0, retry_pods=0,
                    host_pods=len(pods), existing_pods=0, engine="host",
                    host_routed=host_routed, cold_compiles=0,
                    pad_waste_ratio=0.0,
                )
                decisions.record_decision("solver.route", "host",
                                          "no-eligible")
                return self.host.solve(
                    pods,
                    templates,
                    instance_types,
                    existing_nodes=existing_nodes,
                    daemon_overhead=daemon_overhead,
                    limits=limits,
                    volume_topology=volume_topology,
                )
            t0 = time.perf_counter()
            snap = tensorize(
                eligible, templates, instance_types,
                daemon_overhead=daemon_overhead, limits=limits,
                tier_of=tier_of,
            )
            stages["tensorize_ms"] = (time.perf_counter() - t0) * 1000.0
            device_plan = None
        esnap = None
        if existing_nodes:
            if existing_base is not None and device_plan is None:
                # disruption fast path: slice this sub-solve's existing-node
                # tensors out of the round's shared snapshot
                # (ops/consolidate.py DisruptionSnapshot.derive_esnap) —
                # None when a node or group fails to map, and the full
                # build below runs
                esnap = existing_base.derive_esnap(snap, existing_nodes)
            if esnap is None:
                from karpenter_tpu.ops.tensorize import tensorize_existing

                t0 = time.perf_counter()
                esnap = tensorize_existing(snap, existing_nodes, device_plan)
                stages["tensorize_ms"] = stages.get("tensorize_ms", 0.0) + (
                    time.perf_counter() - t0) * 1000.0
        self._route = None
        claims, retry, ecommits = self._run_and_decode(
            snap, esnap, max_bins, stages)
        if self._route is not None:
            # the solve's ONE solver.route verdict: which engine the
            # kernel ultimately ran on (a doubled re-run overwrites — the
            # final rung is the round's answer)
            decisions.record_decision("solver.route", *self._route)
        _pad_padded = devplane.STATS["pad_cells_padded"] - _dp0[2]
        _pad_actual = devplane.STATS["pad_cells_actual"] - _dp0[1]
        self.last_device_stats = dict(
            cold_compiles=devplane.STATS["cold_compiles"] - _dp0[0],
            pad_waste_ratio=(
                round(1.0 - _pad_actual / _pad_padded, 4)
                if _pad_padded > 0 else 0.0
            ),
            groups=snap.G,
            types=snap.T,
            device_pods=len(eligible) - len(retry),
            retry_pods=len(retry),
            host_pods=len(rest),
            existing_pods=sum(len(e[1]) for e in ecommits),
            engine=self._last_engine,
            host_routed=host_routed,
            group_row_cache_hits=_tz_stats.get("group_row_hits", 0) - _rows0[0],
            group_row_cache_misses=(
                _tz_stats.get("group_row_misses", 0) - _rows0[1]),
            **stages,
        )
        # commit device placements onto the existing nodes (deferred so a
        # doubled re-run cannot double-apply); the host pass then sees the
        # updated availability/requirements (existingnode.go Add:64)
        for node, node_pods, delta, merged, gcounts in ecommits:
            node.pods.extend(node_pods)
            node.requests = resutil.merge(node.requests, delta)
            node.requirements = merged
            if has_topology:
                for g, c in gcounts:
                    topology.record_many(snap.groups[g][0], merged, c)
        if has_topology:
            # commit the FINAL claim set into the host topology engine once
            # (a doubled re-run discards its predecessor's claims, so decode
            # itself must not record): register each claim hostname domain
            # (nodeclaim.go:49) and record every landed group with
            # multiplicity (topology.go Record:141), so the host pass and
            # later rounds see the device placements
            for claim in claims:
                claim.topology = topology
                topology.register(wk.HOSTNAME_LABEL, claim.hostname)
                for g, c in getattr(claim, "_gcounts", ()):
                    topology.record_many(snap.groups[g][0], claim.requirements, c)
        # debit nodepool limits for the device-built claims so the host pass
        # can't double-spend them (scheduler.go:292 subtractMax)
        if limits:
            from karpenter_tpu.models.scheduler import subtract_max

            limits = {k: dict(v) for k, v in limits.items()}
            for claim in claims:
                pool = claim.template.nodepool_name
                if pool in limits:
                    limits[pool] = subtract_max(limits[pool], claim.instance_types)
        # leftovers + ineligible pods run through the host loop seeded with
        # the device-built claims (they can still land on those bins)
        if rest or retry:
            return self.host.solve(
                rest + retry,
                templates,
                instance_types,
                topology=topology if has_topology else None,
                existing_nodes=existing_nodes,
                daemon_overhead=daemon_overhead,
                limits=limits,
                initial_claims=claims,
                volume_topology=volume_topology,
            )
        for claim in claims:
            claim.finalize()
        return SchedulerResults(
            new_claims=claims, existing_nodes=existing_nodes, pod_errors={}
        )

    def _run_and_decode(self, snap, esnap, max_bins, stages=None):
        """Estimate the bin axis, dispatch the kernel, decode — PIPELINED:
        when the estimated axis runs dry the doubled re-run is dispatched
        BEFORE the current result is decoded (JAX dispatch is async), so
        the device solves chunk k+1 while the host decodes chunk k. The
        speculative result is discarded when decode proves nothing was left
        over; engines without async dispatch (native C++, mesh-sharded)
        fall back to a lazy synchronous re-run — same result, unpipelined.
        Gates on the kernel's own bin usage, not post-validation claim
        count — a validation-dropped bin must not mask a dry axis, and pure
        validation retries must not spin doubled re-runs."""
        G, T = snap.G, snap.T
        K, W = snap.g_mask.shape[1], snap.W
        R = len(snap.resources)
        M = len(snap.templates)
        total_pods = int(snap.g_count.sum())
        floor = None  # the demand lower bound (the quality account's floor)
        lp_led = False  # the LP relaxation floor steered this solve
        if max_bins:
            B = max_bins
        else:
            # the pack scan is bin-sequential on device, so its latency is
            # proportional to B: size it from a per-resource lower bound
            # (total demand / biggest allocatable) with 1.5x FFD headroom.
            # If the estimate runs out, the unplaced remainder re-runs with
            # a doubled axis (exact, just slower) rather than falling to
            # the host loop.
            demand_tot = (snap.g_demand * snap.g_count[:, None]).sum(axis=0)
            max_alloc = snap.t_alloc.max(axis=0) if T else np.ones(R, dtype=np.float32)
            with np.errstate(divide="ignore", invalid="ignore"):
                lb = np.where(max_alloc > 0, np.ceil(demand_tot / max_alloc), 0.0)
            est = int(np.nanmax(lb)) if lb.size else 1
            # bin-cap topology groups force distinct bins: a cap-c group of
            # n pods needs >= ceil(n/c) bins regardless of resource demand
            # (different capped groups may share bins, so max not sum)
            caps = np.maximum(snap.g_bin_cap.astype(np.int64), 1)
            cap_lb = int(np.ceil(snap.g_count / caps).max()) if G else 0
            # self-conflicting anti classes force one pod per bin ACROSS
            # groups (a decl∩match group conflicts with every other group
            # of its class): class c needs >= sum of those groups' counts
            both = snap.g_decl & snap.g_match  # [G,CW]
            if both.any():
                for w in range(both.shape[1]):
                    live = np.bitwise_or.reduce(both[:, w])
                    for bit in range(32):
                        if not (live >> bit) & 1:
                            continue
                        sel = ((both[:, w] >> bit) & 1).astype(bool)
                        cap_lb = max(cap_lb, int(snap.g_count[sel].sum()))
            # spread classes share the per-bin cap ACROSS groups: class c
            # needs >= ceil(sum of owner counts / cap) distinct bins
            owned = snap.g_sown < SPREAD_OWNED_MIN
            if owned.any():
                cnt = snap.g_count[:, None] * owned  # [G,C]
                cap_c = np.where(owned, snap.g_sown, 1).max(axis=0)  # [C]
                cls_lb = np.ceil(cnt.sum(axis=0) / np.maximum(cap_c, 1)).max()
                cap_lb = max(cap_lb, int(cls_lb))
            est = max(est, min(cap_lb, total_pods))
            # LP relaxation floor (ops/relax.py lp_bin_floor —
            # deploy/README.md "LP relaxation rung"): a weak-duality
            # certified bin lower bound over the SAME demand/capacity/
            # compat tensors, valid whether or not the iteration
            # converged. A raise tightens both the bin-axis sizing
            # below and the solve-quality account's floor; the solve it
            # steers records the solver.route "relax" rung.
            from karpenter_tpu.ops.relax import lp_bin_floor

            lp = lp_bin_floor(snap, est)
            if lp > est:
                est, lp_led = lp, True
            floor = est
            # 1.5x FFD headroom: the doubling re-run below catches a miss
            B = min(max(total_pods, 1), max((3 * est) // 2, 64), 4096)
        Gp, Tp, Bp = _bucket(G), _bucket(T), _bucket(B)

        E = esnap.E if esnap is not None else 0
        Ep = _bucket(max(E, 1), lo=8)
        # one shared assembly point with the batched consolidation probes
        # (ops/consolidate.py): a tensor family added to the snapshot is
        # wired once in kernel_args and reaches both paths
        args = kernel_args(snap, esnap, Gp=Gp, Tp=Tp, Ep=Ep)

        # the level-fill search range shrinks when every type caps its pod
        # count (the kubelet max-pods resource): levels never exceed
        # npods + take <= 2*cap, so ~8 bits replace the generic 20 — the
        # fill is the scan step's dominant op chain
        level_bits = 20
        if resutil.PODS in snap.resources:
            pods_idx = snap.resources.index(resutil.PODS)
            pcap = float(snap.t_alloc[:, pods_idx].max())
            # existing nodes may hold AND absorb more pods than this solve's
            # catalog caps (deprecated type, another pool): the search range
            # must reach npods + remaining pods capacity or the fill
            # silently under-places on them
            if esnap is not None and esnap.e_npods.size:
                e_need = esnap.e_npods + esnap.e_avail[:, pods_idx]
                pcap = max(pcap, float(e_need.max()))
            if 0 < pcap < 1 << 18:
                level_bits = max(4, int(np.ceil(np.log2(2 * pcap + 4))))
        max_minv = int(snap.m_minv.max()) if snap.m_minv.size else 0
        # n_tiers rides the ledger key as a pseudo-static dim: the tier
        # axis is data (same executable either way), but a fused multi-tier
        # solve that lands in a fresh shape family must be ATTRIBUTED to
        # the tier axis in the compile ledger, not read as unexplained
        # churn (deploy/README.md "Fused cluster round")
        base_key = (Gp, Tp, K, W, R, M, snap.off_zone.shape[1],
                    snap.g_decl.shape[1], snap.g_sown.shape[1],
                    snap.g_aneed.shape[1], Ep if esnap is not None else 0,
                    snap.n_tiers)
        compat_cache: dict = {}
        bin_cap = min(total_pods, 4096)
        pull = None
        while True:
            t0 = time.perf_counter()
            # pow-2 ladder waste of THIS dispatch (real G×T×B cells vs the
            # padded shape-bucket volume the scan actually walks); the
            # doubled re-run records its own extents next iteration
            devplane.record_padding("solve.bins", G * T * B, Gp * Tp * Bp)
            # "solve.kernel" brackets the whole dispatch+materialize pair;
            # _invoke's children ("solve.dispatch"/"solve.block"/
            # "solve.native") separate host dispatch cost from the device
            # wait — a speculative pull() spends its time here as pure
            # block (the dispatch already happened last iteration)
            with obs.span("solve.kernel", kind="device", bins=Bp):
                host = pull() if pull is not None else self._invoke(
                    args, base_key + (Bp, level_bits, max_minv), Bp)
            if stages is not None:
                stages["solve_ms"] = stages.get("solve_ms", 0.0) + (
                    time.perf_counter() - t0) * 1000.0
            pull = None
            # replay capsule (obs/capsule.py): this dispatch's exact tensor
            # inputs + outputs by REFERENCE onto the open round trace — an
            # anomalous round serializes the last one next to its Chrome
            # dump. The mesh rung skips here: sharded_solve_host captured
            # the same dispatch at the mesh seam with the shard metadata
            # replay needs (a doubled re-run overwrites — last wins).
            if self._route is None or self._route[0] != "mesh":
                from karpenter_tpu.obs import capsule as _capsule
                from karpenter_tpu.ops.kernels import pallas_enabled

                _capsule.record_capture(
                    "solver.invoke", args, host,
                    engine=self._last_engine,
                    rung=self._route[0] if self._route else None,
                    reason=self._route[1] if self._route else None,
                    max_bins=Bp, level_bits=level_bits, max_minv=max_minv,
                    family=f"{Gp}x{Tp}", pallas=pallas_enabled(),
                )
            used = host["used"]
            exhausted = bool(used[:B].all())
            grow = max_bins is None and exhausted and B < bin_cap
            B2 = min(2 * B, 4096)
            Bp2 = _bucket(B2)
            if grow:
                # double-buffer: the doubled axis dispatches NOW so the
                # device overlaps the decode below (wasted cycles when the
                # decode finds no leftovers — async device time only)
                pull = self._invoke_spec(
                    args, base_key + (Bp2, level_bits, max_minv), Bp2)
            # the RETURNED bin axis, not the requested Bp: the partitioned
            # mesh solve (parallel/mesh.py) merges per-shard budgets into
            # a wider global axis — slicing to Bp would silently drop
            # whole shards' bins and route their pods to the host loop
            assign = host["assign"][:G]
            tmpl = host["tmpl"]
            # F (G×T per-group feasibility) replaces the big per-bin `types`
            # matrix on the host: exact for single-group bins, a sound
            # prefilter for multi-group joint validation
            feas = host["F"][:G, :T]
            assign_e = host["assign_e"][:G, :E] if esnap is not None else None
            t0 = time.perf_counter()
            with obs.span("solve.decode"):
                claims, retry, ecommits = self._decode(
                    snap, esnap, assign, assign_e, used, feas, tmpl,
                    compat_cache)
            if stages is not None:
                stages["decode_ms"] = stages.get("decode_ms", 0.0) + (
                    time.perf_counter() - t0) * 1000.0
            if retry and grow:
                # device bin-axis growth: the doubled re-run keeps axis
                # exhaustion on the device instead of spilling the
                # remainder to the host loop — counted so perf rows can
                # surface bin_growth_events per round
                devplane.record_bin_growth()
                if stages is not None:
                    stages["bin_growths"] = stages.get("bin_growths", 0) + 1
                B, Bp = B2, Bp2
                continue
            if floor is not None and floor > 0 and claims and not retry:
                # solve-quality account: bins opened vs. the demand lower
                # bound this very method computed — the live analog of the
                # perf rows' nodes-vs-floor headline. A steady-state ratio
                # drift fires the solve-overhead-drift anomaly
                # (obs/decisions.py; family = the compiled shape bucket so
                # only comparable solves share a baseline). Retry-bearing
                # solves are excluded: their claims cover only part of the
                # floor's demand, and the artificially low ratio would
                # ratchet the family baseline below what any complete
                # solve can reach — every later healthy solve would then
                # read as drift.
                decisions.record_quality(len(claims), floor,
                                         family=f"{Gp}x{Tp}")
            if lp_led and claims and not retry:
                # The LP floor raised the estimate and the solve it
                # sized completed whole: credit the relax rung so the
                # route ledger distinguishes LP-steered solves from
                # plain kernel routing.
                self._route = ("relax", "ok")
            return claims, retry, ecommits

    def _invoke(self, args, key, max_bins):
        """Run the compiled kernel; returns host numpy dict
        (assign/used/tmpl/F). Overridden by NativeSolver. Large snapshots
        shard over the mesh (groups x types) when one is available.

        Set KARPENTER_PROFILE_DIR to capture a JAX profiler trace of each
        kernel dispatch (the pprof analog, operator.go:174-183; view with
        TensorBoard's profile plugin)."""
        import jax

        # small batches route to the C++ engine: below the crossover the
        # fixed dispatch/tunnel latency dominates anything the accelerator
        # saves (the reference's stance that small batches are cheap,
        # batcher.go:52). Same tensors, same decode — only the kernel swaps.
        cutoff = _native_cutoff()
        min_work = env_int("KARPENTER_DEVICE_MIN_WORK", DEVICE_MIN_WORK)
        total = int(np.asarray(args["g_count"]).sum())
        # REAL counts, not the bucket-padded axes: padded groups have count
        # 0 and padded types zero allocatable, so routing flips at the
        # calibrated work level, not at shape-bucket boundaries
        real_g = int((np.asarray(args["g_count"]) > 0).sum())
        real_t = int((np.asarray(args["t_alloc"]).max(axis=1) > 0).sum())
        work = real_g * real_t
        if cutoff > 0 and total > 0 and (
            total <= cutoff or work < min_work or not _accelerated_backend()
        ):
            native_ok = False
            try:
                from karpenter_tpu import native

                native_ok = native.available()
            except Exception:
                native_ok = False
            if native_ok:
                try:
                    self._last_engine = "native"
                    self._route = ("native",
                                   "small-batch" if total <= cutoff
                                   else "work-floor" if work < min_work
                                   else "cpu-backend")
                    with obs.span("solve.native", kind="device"):
                        return native.solve_step(args, max_bins)
                except Exception:
                    # a real native-engine failure (rc!=0, shape mismatch)
                    # must be visible, not silently eaten by the fallback
                    import logging

                    logging.getLogger(__name__).warning(
                        "native engine failed on a small batch; "
                        "falling back to the device kernel", exc_info=True)
        self._last_engine = "device"
        profile_dir = env_str("KARPENTER_PROFILE_DIR")
        if profile_dir:
            with jax.profiler.trace(profile_dir):
                return self._invoke_inner(args, key, max_bins)
        return self._invoke_inner(args, key, max_bins)

    def _invoke_inner(self, args, key, max_bins):
        import jax

        mesh = self._maybe_mesh()
        G, K, W = args["g_mask"].shape
        T = args["t_mask"].shape[0]
        if mesh is not None and G * T * K * W >= SHARD_MIN_WORK:
            from karpenter_tpu.parallel import sharded_solve_host

            # the shard-stage decomposition (shard.pad/tensorize/dispatch/
            # block/merge device leaves + the mesh.shard compile-ledger
            # family) lives inside the parallel module
            self._route = ("mesh", "ok")
            return sharded_solve_host(mesh, args, max_bins,
                                      level_bits=key[-2])
        self._route = ("xla", "ok")
        # dispatch vs block bracketed separately: JAX dispatch is async, so
        # the first span is host-side launch cost (plus any compile) and
        # the second is the actual device wait — the trace's host/device
        # attribution hinges on this split
        t0 = time.perf_counter()
        with obs.span("solve.dispatch", kind="device"):
            fut = self._kernel(key)(args)
        # a first-sight key pays its XLA compile synchronously inside the
        # dispatch above: that wall time is the ledger's compile record
        devplane.record_dispatch("solve.kernel", key,
                                 time.perf_counter() - t0)
        with obs.span("solve.block", kind="device"):
            flat = np.asarray(fut)  # one device->host pull
        return self._unpack(flat, args, max_bins)

    @staticmethod
    def _unpack(flat, args, max_bins):
        """Split the kernel's single flattened int32 buffer back into the
        assign/assign_e/used/tmpl/F host dict."""
        G = args["g_mask"].shape[0]
        T = args["t_mask"].shape[0]
        B = max_bins
        E = args["e_avail"].shape[0] if "e_avail" in args else 1
        sizes = [G * B, G * E, B, B, G * T]
        offs = np.cumsum([0] + sizes)
        return {
            "assign": flat[offs[0] : offs[1]].reshape(G, B),
            "assign_e": flat[offs[1] : offs[2]].reshape(G, E),
            "used": flat[offs[2] : offs[3]].astype(bool),
            "tmpl": flat[offs[3] : offs[4]],
            "F": flat[offs[4] : offs[5]].reshape(G, T).astype(bool),
        }

    def _invoke_spec(self, args, key, max_bins):
        """Speculative dispatch of the doubled bin axis. On the plain async
        device path the jitted kernel is dispatched immediately — JAX
        returns before the computation finishes — and the materializer pulls
        it later, overlapping the in-flight solve with the host decode. The
        native engine, the mesh-sharded path, and profiled runs are
        synchronous, so they defer the whole _invoke until (and unless) the
        result is actually needed."""
        from karpenter_tpu.ops.kernels import pallas_enabled

        # speculate only when the doubled family's jit wrapper is already
        # warm: a cold key would COMPILE synchronously on dispatch (blocking
        # the host before decode even starts) for a result the decode may
        # prove unnecessary — the lazy fallback pays that only when needed
        warm = (key[-3], pallas_enabled(), key[-2], key[-1]) in _PACKED_KERNELS
        if (
            warm
            and self._last_engine == "device"
            and self._maybe_mesh() is None
            and not env_str("KARPENTER_PROFILE_DIR")
        ):
            try:
                # async dispatch, no block: only the host-side launch cost
                # lands in this span — the wait surfaces later under the
                # next iteration's "solve.kernel"
                t0 = time.perf_counter()
                with obs.span("solve.dispatch_spec", kind="device"):
                    fut = self._kernel(key)(args)
                devplane.record_dispatch("solve.kernel", key,
                                         time.perf_counter() - t0)
            except Exception:
                return lambda: self._invoke(args, key, max_bins)
            return lambda: self._unpack(np.asarray(fut), args, max_bins)
        return lambda: self._invoke(args, key, max_bins)

    def _compat_entry(self, snap, feas, m, gset, template):
        """Distinct-(template, group-set) candidate types + precomputed fit
        thresholds. Candidate types: AND of the device's per-group
        feasibility rows — a sound PREFILTER, not the joint answer: F is
        pairwise (group×type), so it misses three-way value intersections
        (template ∩ pod ∩ type each pairwise-overlap but jointly empty) and
        cross-offering splits. The host re-checks the MERGED requirement set
        on every survivor — exact because the bitmask of the merged set IS
        the value intersection over the interned vocabulary.

        Entries persist across solves in the type-side cache, keyed by
        (template index, per-group signature keys): within one type-side
        entry the groups' F rows, the candidate types, and the merged
        requirement set are all pure functions of that key, so a bin shape
        seen last round skips the whole filter. Invalidation rides the
        type-side cache key (ops/tensorize.py group-row cache contract)."""
        persist = getattr(snap, "compat_cache", None)
        row_keys = getattr(snap, "row_keys", None)
        pkey = None
        if persist is not None and row_keys is not None:
            # the exact-skip knob steers the entry's tsel/exactness arm
            # below, and the type-side key does NOT pin it — it must ride
            # the fingerprint or a knob flip would serve stale entries
            pkey = (m, tuple(row_keys[g] for g in gset),
                    _exact_skip_enabled())
            hit = persist.get(pkey)
            if hit is not None:
                return hit
        bin_reqs = template.requirements.copy()
        for g in gset:
            bin_reqs.add(*snap.group_reqs[g].values())
        joint = feas[gset[0]]
        for g in gset[1:]:
            joint = joint & feas[g]
        tsel = np.flatnonzero(joint & (snap.t_tmpl == m))
        # bins whose merged requirement set provably DECOMPOSES need no
        # merged re-check: group-vs-type is exactly F (masks and offering
        # sets both group-side), template-vs-type was prefiltered into
        # type_refs by the REAL intersection, and the structure below
        # rules out every three-way meet. The standard stamped pool
        # (nodepool label only) hits this on every grid bin, and the
        # partitioned mesh solve's merged multi-group bins (each shard's
        # groups are disjoint slices sharing selector shapes) hit the
        # multi-group arm at 500k scale.
        tmeta = getattr(snap, "_tmpl_keymeta", None)
        if tmeta is None:
            tmeta = [
                (
                    frozenset(tpl.requirements.keys()),
                    wk.TOPOLOGY_ZONE_LABEL not in tpl.requirements
                    and wk.CAPACITY_TYPE_LABEL not in tpl.requirements,
                )
                for tpl in snap.templates
            ]
            snap._tmpl_keymeta = tmeta
        tkeys, off_free = tmeta[m]
        # the decision tree below is the same predicate the old one-liner
        # evaluated — split so the decode.recheck verdict can carry WHY
        # the exactness argument did not apply (obs/decisions.py)
        if not off_free:
            exact, why = False, "offering-keys"
        elif not all(
            tkeys.isdisjoint(snap.group_reqs[g].keys()) for g in gset
        ):
            exact, why = False, "group-key-overlap"
        elif len(gset) == 1 or self._decomposable(snap, gset):
            exact, why = True, "ok"
        elif not _exact_skip_enabled():
            exact, why = False, "disabled"
        else:
            exact, why = False, "non-decomposable"
        decisions.record_decision(
            "decode.recheck", "skip" if exact else "full",
            "no-candidates" if exact and not tsel.size else why)
        if exact and tsel.size:
            # count only bins where a merged re-check was actually
            # avoided — with zero surviving candidates the re-check is a
            # no-op and counting it would overstate the A/B coverage
            from karpenter_tpu.ops.tensorize import STATS as _tz

            _tz["decode_exact_skips"] += 1
        if tsel.size and not exact:
            mask_bin, has_bin, tol_bin = snap.mask_set(bin_reqs)
            tm, th, tt = snap.t_mask[tsel], snap.t_has[tsel], snap.t_tol[tsel]
            shared = th & has_bin[None, :]
            overlap = ((tm & mask_bin[None, :, :]) != 0).any(axis=2)
            # Intersects tolerates an empty meet iff BOTH operators are
            # NotIn/DoesNotExist (requirements.py:249)
            both_tol = tt & tol_bin[None, :]
            req_ok = (~shared | overlap | both_tol).all(axis=1)
            # offerings: available ∧ zone/capacity-type bit of the offering
            # inside the bin's merged allowed sets (the per-offering joint
            # check F cannot express)
            off_ok = snap.off_avail[tsel].copy()
            for label, off_idx in (
                (wk.TOPOLOGY_ZONE_LABEL, snap.off_zone[tsel]),
                (wk.CAPACITY_TYPE_LABEL, snap.off_ct[tsel]),
            ):
                k = snap.key_index.get(label)
                if k is None or not has_bin[k]:
                    continue
                nv = len(snap.vocab[label])
                if nv == 0:
                    # key interned with zero values (e.g. a bare Exists):
                    # offerings that define it cannot exist, ones that
                    # don't (-1) are unconstrained
                    continue
                bits = np.arange(nv)
                allowed = ((mask_bin[k, bits // 32] >> (bits % 32)) & 1).astype(bool)
                off_ok &= np.where(off_idx >= 0, allowed[np.maximum(off_idx, 0)], True)
            ok_rows = req_ok & off_ok.any(axis=1)
            tsel = tsel[ok_rows]
        # object-array gather instead of a per-type Python listcomp: at
        # grid scale (hundreds of bins x hundreds of candidate types) the
        # type_refs tuple-indexing loop alone was ~100ms
        tobj = getattr(snap, "_type_obj_arr", None)
        if tobj is None:
            tobj = np.array([it for _, it in snap.type_refs], dtype=object)
            snap._type_obj_arr = tobj
        objs = list(tobj[tsel]) if tsel.size else []
        # allocatable/capacity rows over the snapshot resource axis with the
        # fit tolerance pre-applied (resutil.fits' constants): the per-bin
        # check reduces to one vectorized compare
        alloc = snap.alloc64()[tsel]
        alloc_thresh = alloc + resutil._EPS + resutil.FIT_REL_EPS * np.abs(alloc)
        tcap = snap.cap64()[tsel]
        entry = (bin_reqs, objs, alloc_thresh, tcap, tsel)
        if pkey is not None:
            from karpenter_tpu.ops.tensorize import _COMPAT_CACHE_MAX

            if len(persist) >= _COMPAT_CACHE_MAX:
                persist.pop(next(iter(persist)))
            persist[pkey] = entry
        return entry

    @staticmethod
    def _decomposable(snap, gset) -> bool:
        """Multi-group arm of the decoder's exact-skip: True when the
        bin's merged requirement set decomposes per key into single-group
        checks F already made — then the merged re-check cannot remove a
        candidate and is skipped outright.

        Exactness argument (the PR-4 single-group reasoning extended to
        the partitioned-shard merged bins, where every bin's groups come
        from one shard's disjoint slice and bursts share a handful of
        selector shapes):

        * **Requirements.** With the template key-disjoint from every
          group (checked by the caller), the merged set's row for key k is
          exactly the row of whichever groups carry k. If a key is carried
          by 2+ groups, we require their (mask, tol) rows BIT-EQUAL — the
          merged row is then that shared row, and the kernel checked it
          against every type for each carrier (F is conjunctive over
          gset). A key carried once decomposes trivially. Three-way meets
          need a shared key with *different* masks — excluded.
        * **Offerings.** F's offering check is per GROUP (zone/ct allowed
          sets ∧ availability, jointly over one offering). The merged bin
          needs ONE offering satisfying every group's zone AND ct sets at
          once, which per-group F cannot promise when different groups
          constrain different offering labels (g1 pins zone, g2 pins ct:
          each F found *some* offering, possibly different ones). We
          therefore require every offering-constraining group (zone or ct
          key present) to agree bit-for-bit on BOTH labels — the joint
          predicate then equals each such group's own F offering check.

        Under both conditions the candidate set after the merged re-check
        equals the F∧template prefilter, so skipping is exact. Cost is a
        few row compares per DISTINCT (template, group-set) key, amortized
        by the compat cache. KARPENTER_DECODE_EXACT_SKIP=0 disables this
        arm for A/B (the seeded parity suite pins on/off equality)."""
        if not _exact_skip_enabled():
            return False
        has = snap.g_has
        mask = snap.g_mask
        tol = snap.g_tol
        K = has.shape[1]
        carriers: list = [None] * K
        for g in gset:
            for k in np.flatnonzero(has[g]):
                first = carriers[k]
                if first is None:
                    carriers[k] = g
                elif (tol[g, k] != tol[first, k]
                      or (mask[g, k] != mask[first, k]).any()):
                    return False
        # offering bundle: zone/ct-constraining groups must agree on both
        zk = snap.key_index.get(wk.TOPOLOGY_ZONE_LABEL)
        ck = snap.key_index.get(wk.CAPACITY_TYPE_LABEL)
        off_keys = [k for k in (zk, ck) if k is not None]
        if off_keys:
            offg = [g for g in gset if any(has[g, k] for k in off_keys)]
            if len(offg) > 1:
                g0 = offg[0]
                for g in offg[1:]:
                    for k in off_keys:
                        if has[g, k] != has[g0, k]:
                            return False
                        if has[g0, k] and (
                            tol[g, k] != tol[g0, k]
                            or (mask[g, k] != mask[g0, k]).any()
                        ):
                            return False
        return True

    def _decode(self, snap, esnap, assign, assign_e, used, feas, tmpl,
                compat_cache=None):
        """Bins → InFlightNodeClaims, with host-side validation of each
        claim's joint instance-type set (the kernel approximates joint
        offering feasibility by intersecting per-group feasibility).
        Existing-node columns decode first (phase-A pods are the head of
        each group) into deferred commit entries — validation is exact
        host-side (requirement compat + float64 fit) and a failed node
        routes its pods to retry without mutating the ExistingNode.
        ``compat_cache`` carries distinct-(template, group-set) entries
        across the doubled re-runs of one solve — F and the snapshot are
        invariant across them, so entries never go stale within a solve."""
        from karpenter_tpu.cloudprovider.types import satisfies_min_values

        cursors = [0] * snap.G
        claims = []
        retry = []
        ecommits = []
        R = len(snap.resources)
        # per-pod demand in float64 from the source dicts — the f32 kernel
        # tensors are too coarse at memory-byte scale; shared by the
        # existing-node and claim decodes
        demand64 = np.array(
            [[d.get(r, 0.0) for r in snap.resources] for d in snap.group_demand],
            dtype=np.float64,
        ).reshape(snap.G, R)
        if esnap is not None and assign_e is not None:
            for e in np.flatnonzero(assign_e.sum(axis=0) > 0):
                node = esnap.nodes[int(e)]
                counts = assign_e[:, e]
                gidx = np.flatnonzero(counts)
                merged = node.requirements.copy()
                node_pods = []
                gcounts = []
                ok = True
                for g in gidx:
                    reqs = snap.group_reqs[int(g)]
                    if merged.compatible(reqs) is not None:
                        ok = False
                        break
                    merged.add(*reqs.values())
                req_vec = counts[gidx].astype(np.float64) @ demand64[gidx]
                delta = {
                    r: float(v)
                    for r, v in zip(snap.resources, req_vec.tolist())
                    if v > 0
                }
                if ok:
                    total = resutil.merge(node.requests, delta)
                    ok = resutil.fits(total, node.cached_available)
                for g in gidx:
                    c = int(counts[g])
                    taken = snap.groups[int(g)][cursors[int(g)] : cursors[int(g)] + c]
                    cursors[int(g)] += c
                    if ok:
                        node_pods.extend(taken)
                        gcounts.append((int(g), c))
                    else:
                        retry.extend(taken)
                if ok:
                    ecommits.append((node, node_pods, delta, merged, gcounts))
        topology = NullTopology()
        # nodepool-limit accounting mirroring the kernel's (and the
        # reference's, scheduler.go:270-292): a bin's candidate types are
        # filtered to those whose worst-case capacity fits the remaining
        # limits at open time, and the surviving worst case is debited.
        # Without this the F-based candidates resurrect over-limit types
        # the kernel never would have opened, and the host pass then grows
        # the claim past the nodepool limit.
        rem_limits = snap.m_limits.astype(np.float64).copy()
        Bax = assign.shape[1]
        cols = np.flatnonzero(used[:Bax] & (assign.sum(axis=0) > 0))
        breq = assign[:, cols].T.astype(np.float64) @ demand64
        breq += snap.m_overhead.astype(np.float64)[tmpl[cols]]
        # bins sharing a (template, group-composition) key have identical
        # requirements, so the expensive requirement∧offering compat filter
        # runs once per distinct key; per-bin work is only the resource-fit
        # check (many bins are clones in a deployment burst)
        if compat_cache is None:
            compat_cache = {}
        # all (group, bin) memberships in one pass instead of a per-column
        # flatnonzero inside the loop
        sub = assign[:, cols]
        nz_ci, nz_gi = np.nonzero(sub.T)  # (bin-column, group) pairs, ci-major
        counts_flat = sub.T[nz_ci, nz_gi]
        row_starts = np.searchsorted(nz_ci, np.arange(len(cols)))
        row_ends = np.append(row_starts[1:], len(nz_ci))
        tmpl_cols = tmpl[cols]
        overhead_dicts = [
            dict(zip(snap.resources, row.tolist())) for row in snap.m_overhead
        ]
        # pass 1: per-bin memberships + cache keys (cursor order is the
        # column order; pods within a group are identical, so any
        # consistent slicing is spec-equivalent)
        bin_keys = []
        bin_meta = []  # (m, bin_pods, gcounts)
        key_rows: dict = {}  # key -> [ci...]
        for ci in range(len(cols)):
            m = int(tmpl_cols[ci])
            bin_pods = []
            gset = []
            gcounts = []
            for j in range(row_starts[ci], row_ends[ci]):
                g = int(nz_gi[j])
                c = int(counts_flat[j])
                gset.append(g)
                gcounts.append((g, c))
                bin_pods.extend(snap.groups[g][cursors[g] : cursors[g] + c])
                cursors[g] += c
            key = (m, tuple(gset))
            bin_keys.append(key)
            bin_meta.append((m, bin_pods, gcounts))
            key_rows.setdefault(key, []).append(ci)

        # pass 2: distinct-key candidate sets + BATCHED resource fit (one
        # numpy reduction per key instead of two per bin); nodepool limits
        # keep the sequential per-bin path since the debit evolves
        no_limits = not np.isfinite(snap.m_limits).any()
        fit_rows = [None] * len(cols)
        its_rows = [None] * len(cols)
        for key, rows in key_rows.items():
            m, gset = key[0], list(key[1])
            template = snap.templates[m]
            cached = compat_cache.get(key)
            if cached is None:
                cached = self._compat_entry(snap, feas, m, gset, template)
                compat_cache[key] = cached
            _, objs, alloc_thresh, _, _ = cached
            rb = breq[rows]
            if no_limits:
                if len(rows) == 1:
                    # the common grid shape: every bin its own key — skip
                    # the np.unique machinery (it was ~20% of decode)
                    row = (rb[0] <= alloc_thresh).all(axis=1)
                    fit_rows[rows[0]] = row
                    its_rows[rows[0]] = (
                        objs if row.all() else [objs[i] for i in np.flatnonzero(row)]
                    )
                    continue
                # clone bins (same key, same totals) share their candidate
                # list outright: one fit reduction and one list build per
                # DISTINCT demand vector, not per bin
                ub, inv = np.unique(rb, axis=0, return_inverse=True)
                ufits = (ub[:, None, :] <= alloc_thresh[None, :, :]).all(axis=2)
                uits = [
                    objs if row.all() else [objs[i] for i in np.flatnonzero(row)]
                    for row in ufits
                ]
                for i, ci in enumerate(rows):
                    fit_rows[ci] = ufits[inv[i]]
                    its_rows[ci] = uits[inv[i]]
            else:
                fits = (rb[:, None, :] <= alloc_thresh[None, :, :]).all(axis=2)
                for i, ci in enumerate(rows):
                    fit_rows[ci] = fits[i]

        for ci in range(len(cols)):
            m, bin_pods, gcounts = bin_meta[ci]
            template = snap.templates[m]
            req_vec = breq[ci]
            requests = {
                r: float(v) for r, v in zip(snap.resources, req_vec.tolist()) if v > 0
            }
            bin_reqs, objs, _alloc_thresh, tcap, _ = compat_cache[bin_keys[ci]]
            ok = fit_rows[ci]
            if no_limits:
                its = its_rows[ci]  # InFlightNodeClaim copies its input list
            else:
                ok = ok & (
                    tcap <= rem_limits[m] + resutil._EPS
                    + resutil.FIT_REL_EPS * np.abs(rem_limits[m])
                ).all(axis=1)
                its = [objs[i] for i in np.flatnonzero(ok)]
            # bin_reqs already is template ∪ groups: hand the constructor a
            # copy directly (it adds its own hostname row) instead of
            # building the template set and re-intersecting per bin
            claim = InFlightNodeClaim(
                template,
                topology,
                overhead_dicts[m],
                its,
                requirements=bin_reqs.copy(),
            )
            claim.pods = bin_pods
            claim.requests = requests
            remaining = claim.instance_types
            if remaining and claim.requirements.has_min_values():
                _, err = satisfies_min_values(remaining, claim.requirements)
                if err:
                    remaining = []
            if not remaining:
                retry.extend(bin_pods)
                continue
            claim.instance_types = remaining
            # debit only once the claim survives validation — a bin dropped
            # to retry must not consume limit budget for later bins
            if not no_limits:
                rem_limits[m] -= tcap[ok].max(axis=0)
            claim._gcounts = gcounts  # for the solver's topology commit
            if snap.g_tier is not None and gcounts:
                # tier of the bin's OPENING group (the first group index
                # with pods here — group order IS scan order, and a bin is
                # first used at its opening step), so the fused admission
                # round can charge each claim to the tier that opened it
                claim._tier = int(snap.g_tier[gcounts[0][0]])
            claims.append(claim)
        # pods the kernel couldn't place (unsched counts are implied by the
        # unconsumed remainder of each group)
        for g in range(snap.G):
            retry.extend(snap.groups[g][cursors[g] :])
        return claims, retry, ecommits


class NativeSolver(TPUSolver):
    """Same tensorize→kernel→decode pipeline with the C++ host engine
    (karpenter_tpu/native) in place of the XLA kernel — the fast fallback
    when no accelerator is reachable (BASELINE.md: in-process heuristic on
    host CPU). Shapes need no bucketing, but the shared path pads anyway;
    padded groups/types are inert (count 0 / alloc 0)."""

    def _kernel(self, key):  # pragma: no cover - never compiled
        raise AssertionError("NativeSolver does not compile XLA kernels")

    def _invoke(self, args, key, max_bins):
        from karpenter_tpu import native

        self._last_engine = "native"
        self._route = ("native", "ok")
        return native.solve_step(args, max_bins)


def make_solver(prefer_device: bool = True) -> Solver:
    """Device kernel if jax is importable, else the C++ host engine, else
    the pure-Python FFD loop (the reference algorithm)."""
    if prefer_device:
        try:
            import jax  # noqa: F401

            return TPUSolver()
        except Exception:
            pass
    try:
        from karpenter_tpu import native

        if native.available():
            return NativeSolver()
    except Exception:
        pass
    return HostSolver()
