"""Solver: the pluggable device/host boundary.

The reference has a single in-process Go loop; our build exposes a `Solver`
seam (the analog of the metrics-decorator precedent around CloudProvider,
SURVEY.md §2.3): `TPUSolver` compiles the snapshot to tensors and runs the
batched feasibility+pack kernels on the accelerator, then decodes bins back
into in-flight NodeClaims and validates them host-side; anything the device
path can't express (pod affinity, topology waves before M2, validation
failures, leftovers) flows through `HostSolver` — the faithful FFD loop —
seeded with the device-produced claims. Shapes are bucketed so XLA compiles
once per bucket.
"""

from __future__ import annotations

import math

import numpy as np

from karpenter_tpu.models.inflight import InFlightNodeClaim
from karpenter_tpu.models.scheduler import NullTopology, Scheduler, SchedulerResults
from karpenter_tpu.ops import tensorize
from karpenter_tpu.ops.tensorize import device_eligible
from karpenter_tpu.utils import resources as resutil


class Solver:
    def solve(self, pods, templates, instance_types, **kw) -> SchedulerResults:
        raise NotImplementedError


class HostSolver(Solver):
    """The reference algorithm (FFD loop) on the host. Fallback + oracle."""

    def solve(
        self,
        pods,
        templates,
        instance_types,
        topology=None,
        existing_nodes=(),
        daemon_overhead=None,
        limits=None,
        initial_claims=(),
        volume_topology=None,
    ) -> SchedulerResults:
        sched = Scheduler(
            templates,
            instance_types,
            topology=topology,
            existing_nodes=existing_nodes,
            daemon_overhead=daemon_overhead,
            remaining_resources=limits,
            volume_topology=volume_topology,
        )
        sched.new_claims = list(initial_claims)
        return sched.solve(pods)


def _bucket(n: int, lo: int = 16) -> int:
    return max(lo, 1 << math.ceil(math.log2(max(n, 1))))


class TPUSolver(Solver):
    def __init__(self):
        self._compiled = {}
        self.host = HostSolver()
        self.last_device_stats: dict = {}

    def _kernel(self, key):
        if key not in self._compiled:
            import functools

            import jax

            from karpenter_tpu.ops import kernels

            max_bins = key[-1]
            self._compiled[key] = jax.jit(
                functools.partial(kernels.solve_step, max_bins=max_bins)
            )
        return self._compiled[key]

    def solve(
        self,
        pods,
        templates,
        instance_types,
        topology=None,
        existing_nodes=(),
        daemon_overhead=None,
        limits=None,
        max_bins: int | None = None,
        volume_topology=None,
    ) -> SchedulerResults:
        # Existing-node scheduling and topology-group waves join the device
        # path incrementally; those snapshots route through the host loop.
        has_topology = bool(getattr(topology, "has_groups", topology is not None and not isinstance(topology, NullTopology)))
        if existing_nodes or has_topology or not templates:
            return self.host.solve(
                pods,
                templates,
                instance_types,
                topology=topology,
                existing_nodes=existing_nodes,
                daemon_overhead=daemon_overhead,
                limits=limits,
                volume_topology=volume_topology,
            )

        # weight order decides which template a new bin opens from
        # (scheduler.go:267 tries templates in weight order)
        templates = sorted(templates, key=lambda t: (-t.weight, t.nodepool_name))

        eligible = [p for p in pods if device_eligible(p)]
        rest = [p for p in pods if not device_eligible(p)]
        if not eligible:
            return self.host.solve(
                pods,
                templates,
                instance_types,
                daemon_overhead=daemon_overhead,
                limits=limits,
                volume_topology=volume_topology,
            )

        snap = tensorize(
            eligible, templates, instance_types, daemon_overhead=daemon_overhead, limits=limits
        )
        claims, retry = self._run_and_decode(snap, max_bins)
        self.last_device_stats = dict(
            groups=snap.G,
            types=snap.T,
            device_pods=len(eligible) - len(retry),
            retry_pods=len(retry),
            host_pods=len(rest),
        )
        # debit nodepool limits for the device-built claims so the host pass
        # can't double-spend them (scheduler.go:292 subtractMax)
        if limits:
            from karpenter_tpu.models.scheduler import subtract_max

            limits = {k: dict(v) for k, v in limits.items()}
            for claim in claims:
                pool = claim.template.nodepool_name
                if pool in limits:
                    limits[pool] = subtract_max(limits[pool], claim.instance_types)
        # leftovers + ineligible pods run through the host loop seeded with
        # the device-built claims (they can still land on those bins)
        if rest or retry:
            return self.host.solve(
                rest + retry,
                templates,
                instance_types,
                daemon_overhead=daemon_overhead,
                limits=limits,
                initial_claims=claims,
                volume_topology=volume_topology,
            )
        for claim in claims:
            claim.finalize()
        return SchedulerResults(new_claims=claims, existing_nodes=[], pod_errors={})

    def _run_and_decode(self, snap, max_bins):
        G, T = snap.G, snap.T
        K, W = snap.g_mask.shape[1], snap.W
        R = len(snap.resources)
        M = len(snap.templates)
        total_pods = int(snap.g_count.sum())
        B = max_bins or min(max(total_pods, 1), 4096)
        Gp, Tp, Bp = _bucket(G), _bucket(T), _bucket(B)

        def pad(a, shape):
            out = np.zeros(shape, dtype=a.dtype)
            out[tuple(slice(0, s) for s in a.shape)] = a
            return out

        args = dict(
            g_mask=pad(snap.g_mask, (Gp, K, W)),
            g_has=pad(snap.g_has, (Gp, K)),
            g_demand=pad(snap.g_demand, (Gp, R)),
            g_count=pad(snap.g_count, (Gp,)),
            g_zone_allowed=pad(snap.g_zone_allowed, (Gp, snap.g_zone_allowed.shape[1])),
            g_ct_allowed=pad(snap.g_ct_allowed, (Gp, snap.g_ct_allowed.shape[1])),
            g_tmpl_ok=pad(snap.g_tmpl_ok, (Gp, M)),
            t_mask=pad(snap.t_mask, (Tp, K, W)),
            t_has=pad(snap.t_has, (Tp, K)),
            t_alloc=pad(snap.t_alloc, (Tp, R)),
            t_cap=pad(snap.t_cap, (Tp, R)),
            t_tmpl=pad(snap.t_tmpl, (Tp,)),
            off_zone=np.full((Tp, snap.off_zone.shape[1]), -1, dtype=np.int32),
            off_ct=np.full((Tp, snap.off_ct.shape[1]), -1, dtype=np.int32),
            off_avail=pad(snap.off_avail, (Tp, snap.off_avail.shape[1])),
            off_price=pad(snap.off_price, (Tp, snap.off_price.shape[1])),
            m_mask=snap.m_mask,
            m_has=snap.m_has,
            m_overhead=snap.m_overhead,
            m_limits=snap.m_limits,
        )
        args["off_zone"][:T] = snap.off_zone
        args["off_ct"][:T] = snap.off_ct
        # padded types must be infeasible: zero alloc fails fits (pods>=1)

        key = (Gp, Tp, K, W, R, M, snap.off_zone.shape[1], Bp)
        out = self._kernel(key)(args)
        assign = np.asarray(out["assign"])[:G, :Bp]
        used = np.asarray(out["used"])
        types = np.asarray(out["types"])[:, :T]
        tmpl = np.asarray(out["tmpl"])

        return self._decode(snap, assign, used, types, tmpl)

    def _decode(self, snap, assign, used, types, tmpl):
        """Bins → InFlightNodeClaims, with host-side validation of each
        claim's joint instance-type set (the kernel approximates joint
        offering feasibility by intersecting per-group feasibility)."""
        from karpenter_tpu.cloudprovider.types import filter_instance_types, satisfies_min_values

        cursors = [0] * snap.G
        claims = []
        retry = []
        topology = NullTopology()
        for b in range(assign.shape[1]):
            if not used[b] or assign[:, b].sum() == 0:
                continue
            m = int(tmpl[b])
            template = snap.templates[m]
            bin_pods = []
            bin_reqs = template.requirements.copy()
            # requests accumulate in float64 from the source demand dicts —
            # the f32 kernel tensors are too coarse at memory-byte scale
            requests = {
                r: float(v)
                for r, v in zip(snap.resources, snap.m_overhead[m].tolist())
                if v > 0
            }
            for g in range(snap.G):
                c = int(assign[g, b])
                if c == 0:
                    continue
                bin_pods.extend(snap.groups[g][cursors[g] : cursors[g] + c])
                cursors[g] += c
                bin_reqs.add(*snap.group_reqs[g].values())
                requests = resutil.merge(
                    requests, {r: v * c for r, v in snap.group_demand[g].items()}
                )
            its = [snap.type_refs[t][1] for t in range(snap.T) if types[b, t] and snap.type_refs[t][0] == m]
            claim = InFlightNodeClaim(
                template,
                topology,
                dict(zip(snap.resources, snap.m_overhead[m].tolist())),
                its,
            )
            claim.pods = bin_pods
            claim.requests = requests
            claim.requirements.add(*bin_reqs.values())
            # host-side joint validation
            remaining = filter_instance_types(claim.instance_types, claim.requirements, claim.requests)
            if remaining and claim.requirements.has_min_values():
                _, err = satisfies_min_values(remaining, claim.requirements)
                if err:
                    remaining = []
            if not remaining:
                retry.extend(bin_pods)
                continue
            claim.instance_types = remaining
            claims.append(claim)
        # pods the kernel couldn't place (unsched counts are implied by the
        # unconsumed remainder of each group)
        for g in range(snap.G):
            retry.extend(snap.groups[g][cursors[g] :])
        return claims, retry


def make_solver(prefer_device: bool = True) -> Solver:
    if not prefer_device:
        return HostSolver()
    try:
        import jax  # noqa: F401

        return TPUSolver()
    except Exception:  # pragma: no cover - jax is baked into this image
        return HostSolver()
