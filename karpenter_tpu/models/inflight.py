"""In-flight NodeClaim simulation: the unit of bin-packing.

Behavioral mirror of the reference's scheduling NodeClaim
(pkg/controllers/provisioning/scheduling/nodeclaim.go:65-120: taints →
host ports → requirement compatibility → topology tightening → instance-type
filtering) and NodeClaimTemplate (nodeclaimtemplate.go:39-61). A claim keeps
EVERY instance type still feasible for its accumulated pods; its effective
capacity is therefore the max over remaining types, which the device pack
kernel (ops/kernels.py) replicates.
"""

from __future__ import annotations

import itertools

from karpenter_tpu.api import labels as wk
from karpenter_tpu.cloudprovider.types import (
    filter_instance_types,
    satisfies_min_values,
    truncate_instance_types,
)
from karpenter_tpu.scheduling import (
    IN,
    HostPortUsage,
    Requirement,
    Requirements,
    Taints,
    has_preferred_node_affinity,
    label_requirements,
    node_selector_requirements,
    pod_requirements,
    strict_pod_requirements,
)
from karpenter_tpu.utils import resources as resutil

_hostname_counter = itertools.count(1)

# Instance types kept on a launched claim (nodeclaimtemplate.go:34)
MAX_INSTANCE_TYPES = 60


class ClaimTemplate:
    """NodePool → stamped claim template (nodeclaimtemplate.go:39)."""

    def __init__(self, node_pool):
        self.node_pool = node_pool
        self.nodepool_name = node_pool.name
        self.weight = node_pool.spec.weight
        t = node_pool.spec.template
        self.labels = dict(t.labels)
        # claims carry the pool's static-field hash; the drift condition
        # controller compares it against the pool's current annotation
        # (nodeclaimtemplate.go stamps karpenter.sh/nodepool-hash)
        self.annotations = {
            **t.annotations,
            wk.NODEPOOL_HASH_ANNOTATION: node_pool.static_hash(),
            wk.NODEPOOL_HASH_VERSION_ANNOTATION: wk.NODEPOOL_HASH_VERSION,
        }
        self.taints = Taints(t.taints)
        self.startup_taints = Taints(t.startup_taints)
        self.kubelet = dict(t.kubelet)
        self.node_class_ref = dict(t.node_class_ref)
        self.requirements = Requirements()
        self.requirements.add(*node_selector_requirements(t.requirements).values())
        self.requirements.add(*label_requirements(t.labels).values())
        self.requirements.add(Requirement(wk.NODEPOOL_LABEL, IN, [node_pool.name]))


class InFlightNodeClaim:
    """One hypothetical node being packed (scheduling/nodeclaim.go)."""

    def __init__(self, template: ClaimTemplate, topology, daemon_resources: dict, instance_types, requirements=None):
        self.template = template
        self.topology = topology
        self.daemon_resources = dict(daemon_resources or {})
        self.instance_types = list(instance_types)
        self.pods: list = []
        self.requests = dict(self.daemon_resources)
        # `requirements` lets the device decoder hand over the bin's merged
        # set directly (it already contains the template's), skipping a
        # copy per decoded claim; the set is owned by the claim afterwards
        self.requirements = (
            template.requirements.copy() if requirements is None else requirements
        )
        # nodes need hostnames for hostname-topology purposes; dropped at
        # finalize (scheduler.go FinalizeScheduling)
        self.hostname = f"hostname-{next(_hostname_counter)}"
        self.requirements.add(Requirement(wk.HOSTNAME_LABEL, IN, [self.hostname]))
        topology.register(wk.HOSTNAME_LABEL, self.hostname)  # nodeclaim.go:49
        self.taints = Taints(template.taints)
        self.host_ports = HostPortUsage()

    def add(self, pod) -> str | None:
        """Try to schedule pod onto this claim; returns error string or None.
        Mutates only on success (nodeclaim.go Add:65)."""
        err = self.taints.tolerates(pod)
        if err:
            return err
        err = self.host_ports.conflicts(pod)
        if err:
            return f"checking host port usage, {err}"

        claim_reqs = self.requirements.copy()
        pod_reqs = pod_requirements(pod)
        err = claim_reqs.compatible(pod_reqs, allow_undefined=wk.WELL_KNOWN_LABELS)
        if err:
            return f"incompatible requirements, {err}"
        claim_reqs.add(*pod_reqs.values())

        # preferred node affinity must not restrict topology domains
        strict = strict_pod_requirements(pod) if has_preferred_node_affinity(pod) else pod_reqs
        topo_reqs, err = self.topology.add_requirements(
            strict, claim_reqs, pod, allow_undefined=wk.WELL_KNOWN_LABELS
        )
        if err:
            return err
        err = claim_reqs.compatible(topo_reqs, allow_undefined=wk.WELL_KNOWN_LABELS)
        if err:
            return err
        claim_reqs.add(*topo_reqs.values())

        requests = resutil.merge(self.requests, pod.effective_requests())
        remaining = filter_instance_types(self.instance_types, claim_reqs, requests)
        if remaining and claim_reqs.has_min_values():
            _, mv_err = satisfies_min_values(remaining, claim_reqs)
            if mv_err:
                remaining = []
        if not remaining:
            return (
                f"no instance type satisfied resources {requests} and "
                f"requirements {claim_reqs}"
            )

        self.pods.append(pod)
        self.instance_types = remaining
        self.requests = requests
        self.requirements = claim_reqs
        self.topology.record(pod, claim_reqs, allow_undefined=wk.WELL_KNOWN_LABELS)
        self.host_ports.add(pod)
        return None

    def finalize(self):
        """Drop the synthetic hostname requirement before launch
        (nodeclaim.go FinalizeScheduling)."""
        self.requirements.pop(wk.HOSTNAME_LABEL, None)

    def truncate_instance_types(self, max_items: int = MAX_INSTANCE_TYPES):
        out, err = truncate_instance_types(self.instance_types, self.requirements, max_items)
        if err is None:
            self.instance_types = out
        return err

    def to_node_claim(self):
        """Emit the launchable NodeClaim object (nodeclaimtemplate.go
        ToNodeClaim:39-61)."""
        from karpenter_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec
        from karpenter_tpu.api.objects import ObjectMeta, new_uid

        reqs = [r.to_node_selector_requirement() for r in self.requirements.values()]
        name = f"{self.template.nodepool_name}-{new_uid('claim')}"
        labels = {
            **self.template.labels,
            **self.requirements.labels(),
            wk.NODEPOOL_LABEL: self.template.nodepool_name,
        }
        return NodeClaim(
            metadata=ObjectMeta(
                name=name,
                namespace="",
                labels=labels,
                annotations=dict(self.template.annotations),
                finalizers=[wk.TERMINATION_FINALIZER],
            ),
            spec=NodeClaimSpec(
                taints=list(self.template.taints),
                startup_taints=list(self.template.startup_taints),
                requirements=reqs,
                resource_requests=dict(self.requests),
                kubelet=dict(self.template.kubelet),
                node_class_ref=dict(self.template.node_class_ref),
            ),
        )

    @property
    def price_floor(self) -> float:
        """Cheapest possible launch price among remaining options."""
        best = float("inf")
        for it in self.instance_types:
            ofs = it.offerings.available().compatible(self.requirements)
            if ofs:
                best = min(best, ofs.cheapest().price)
        return best

    def __repr__(self):
        return (
            f"InFlightNodeClaim(pool={self.template.nodepool_name}, pods={len(self.pods)}, "
            f"types={len(self.instance_types)})"
        )
