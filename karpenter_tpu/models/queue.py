"""First-fit-decreasing pod queue with staleness detection.

Mirrors the reference's scheduling queue (pkg/controllers/provisioning/
scheduling/queue.go:37-76): pods ordered by CPU then memory descending,
Pop returns False once the queue has cycled without progress, Push after a
relaxation resets staleness tracking.
"""

from __future__ import annotations

from collections import deque

from karpenter_tpu.utils import resources as resutil


def _sort_key(pod):
    req = pod.effective_requests()
    return (-req.get(resutil.CPU, 0.0), -req.get(resutil.MEMORY, 0.0))


class SchedulingQueue:
    def __init__(self, pods):
        self.pods = deque(sorted(pods, key=_sort_key))
        self._last_len: dict = {}

    def pop(self):
        if not self.pods:
            return None
        p = self.pods[0]
        # cycled through the whole queue without progress → stop
        if self._last_len.get(p.uid) == len(self.pods):
            return None
        self.pods.popleft()
        return p

    def push(self, pod, relaxed: bool):
        self.pods.append(pod)
        if relaxed:
            self._last_len = {}
        else:
            self._last_len[pod.uid] = len(self.pods)

    def __len__(self):
        return len(self.pods)
