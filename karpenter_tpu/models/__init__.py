from karpenter_tpu.models.inflight import ClaimTemplate, InFlightNodeClaim  # noqa: F401
from karpenter_tpu.models.queue import SchedulingQueue  # noqa: F401
from karpenter_tpu.models.scheduler import Scheduler, SchedulerResults  # noqa: F401
from karpenter_tpu.models.solver import (  # noqa: F401
    HostSolver,
    NativeSolver,
    Solver,
    TPUSolver,
    make_solver,
)

__all__ = [
    "ClaimTemplate", "InFlightNodeClaim", "SchedulingQueue", "Scheduler",
    "SchedulerResults", "HostSolver", "NativeSolver", "Solver", "TPUSolver",
    "make_solver",
]
