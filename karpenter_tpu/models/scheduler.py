"""The host-plane scheduling loop (first-fit-decreasing).

Behavioral mirror of the reference's Scheduler.Solve
(pkg/controllers/provisioning/scheduling/scheduler.go:195-296): pop pods in
FFD order; try existing nodes, then open claims sorted by ascending pod
count, then a new claim per weight-ordered template (respecting nodepool
limits via remaining-resource filtering); on failure relax preferences and
requeue. This loop is both the semantic oracle for the device kernel and the
no-accelerator fallback solver.
"""

from __future__ import annotations

from karpenter_tpu.models.inflight import ClaimTemplate, InFlightNodeClaim
from karpenter_tpu.models.preferences import Preferences
from karpenter_tpu.models.queue import SchedulingQueue
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.utils import resources as resutil


class NullTopology:
    """Topology hooks when no topology constraints are in play (M2 supplies
    the real implementation)."""

    def add_requirements(self, strict_pod_reqs, node_reqs, pod, allow_undefined=None):
        return Requirements(), None

    def record(self, pod, reqs, allow_undefined=None):
        pass

    def update(self, pod):
        return None

    def register(self, topology_key, domain):
        pass


class SchedulerResults:
    """Solve output (scheduler.go Results:96)."""

    def __init__(self, new_claims, existing_nodes, pod_errors):
        self.new_claims = new_claims
        self.existing_nodes = existing_nodes
        self.pod_errors = pod_errors

    def all_pods_scheduled(self) -> bool:
        return not self.pod_errors

    def truncate_instance_types(self, max_items=None):
        from karpenter_tpu.models.inflight import MAX_INSTANCE_TYPES

        for claim in self.new_claims:
            claim.truncate_instance_types(max_items or MAX_INSTANCE_TYPES)
        return self

    def node_count(self) -> int:
        return len(self.new_claims)

    def scheduled_pod_count(self) -> int:
        n = sum(len(c.pods) for c in self.new_claims)
        n += sum(len(getattr(e, "scheduled_pods", [])) for e in self.existing_nodes)
        return n


def filter_by_remaining_resources(instance_types, remaining: dict | None):
    """Drop types whose full capacity would breach the nodepool's remaining
    limits; only the limited resource names constrain
    (scheduler.go filterByRemainingResources:378)."""
    if remaining is None:
        return list(instance_types)
    return [
        it
        for it in instance_types
        if all(it.capacity.get(r, 0.0) <= v + 1e-9 for r, v in remaining.items())
    ]


def subtract_max(remaining: dict, instance_types) -> dict:
    """Subtract the worst-case (max per-resource) capacity of the claim's
    remaining types; only limited resource names are tracked
    (scheduler.go subtractMax)."""
    worst = resutil.max_resources(*[it.capacity for it in instance_types])
    return {r: v - worst.get(r, 0.0) for r, v in remaining.items()}


class Scheduler:
    def __init__(
        self,
        templates,  # [ClaimTemplate] in weight order
        instance_types: dict,  # nodepool name -> [InstanceType]
        topology=None,
        existing_nodes=(),
        daemon_overhead: dict | None = None,  # nodepool name -> ResourceList
        remaining_resources: dict | None = None,  # nodepool name -> ResourceList (limits)
        recorder=None,
        volume_topology=None,  # VolumeTopology: PV/SC zone pins (volumetopology.go:42)
    ):
        self.templates = sorted(templates, key=lambda t: (-t.weight, t.nodepool_name))
        self.instance_types = instance_types
        self.topology = topology or NullTopology()
        self.existing_nodes = list(existing_nodes)
        self.daemon_overhead = daemon_overhead or {}
        self.remaining_resources = dict(remaining_resources or {})
        self.preferences = Preferences()
        self.recorder = recorder
        self.volume_topology = volume_topology
        self.new_claims: list = []

    def solve(self, pods) -> SchedulerResults:
        # relaxation mutates pod specs in place; work on clones so a caller
        # can re-solve the same input and get the same answer, but hand the
        # caller's own objects back in the results
        originals = {p.uid: p for p in pods}
        pods = [p.clone() for p in pods]
        if self.volume_topology is not None:
            # zone pins from bound PVs / storage classes AND into the
            # clones' node affinity; the caller's objects stay untouched
            for p in pods:
                self.volume_topology.inject(p)
        errors: dict = {}
        pod_by_uid = {}
        q = SchedulingQueue(pods)
        while True:
            pod = q.pop()
            if pod is None:
                break
            pod_by_uid[pod.uid] = pod
            err = self._add(pod)
            errors[pod.uid] = err
            if err is None:
                continue
            # relax preferences and recompute topology (scheduler.go:223)
            relaxed = self.preferences.relax(pod)
            q.push(pod, relaxed)
            if relaxed:
                self.topology.update(pod)
        for claim in self.new_claims:
            claim.finalize()
            claim.pods = [originals.get(p.uid, p) for p in claim.pods]
        for node in self.existing_nodes:
            if hasattr(node, "pods"):
                node.pods = [originals.get(p.uid, p) for p in node.pods]
        pod_errors = {
            uid: err for uid, err in errors.items() if err is not None
        }
        return SchedulerResults(
            new_claims=self.new_claims,
            existing_nodes=self.existing_nodes,
            pod_errors={pod_by_uid[uid].key(): e for uid, e in pod_errors.items()},
        )

    def _add(self, pod) -> str | None:
        # 1. in-flight real nodes first (scheduler.go:250)
        for node in self.existing_nodes:
            if node.add(pod) is None:
                return None
        # 2. open claims, emptiest first (scheduler.go:258)
        self.new_claims.sort(key=lambda c: len(c.pods))
        for claim in self.new_claims:
            if claim.add(pod) is None:
                return None
        # 3. new claim per template in weight order (scheduler.go:267)
        errs = []
        for template in self.templates:
            its = self.instance_types.get(template.nodepool_name, [])
            remaining = self.remaining_resources.get(template.nodepool_name)
            if remaining is not None:
                its = filter_by_remaining_resources(its, remaining)
                if not its:
                    errs.append(
                        f'all available instance types exceed limits for nodepool: "{template.nodepool_name}"'
                    )
                    continue
            claim = InFlightNodeClaim(
                template,
                self.topology,
                self.daemon_overhead.get(template.nodepool_name, {}),
                its,
            )
            err = claim.add(pod)
            if err is not None:
                errs.append(f'incompatible with nodepool "{template.nodepool_name}", {err}')
                continue
            self.new_claims.append(claim)
            if remaining is not None:
                self.remaining_resources[template.nodepool_name] = subtract_max(
                    remaining, claim.instance_types
                )
            return None
        return "; ".join(errs) if errs else "no nodepool available"
