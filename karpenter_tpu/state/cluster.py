"""Cluster: the in-memory mirror of apiserver state.

Behavioral mirror of the reference's pkg/controllers/state/cluster.go:47-84:
nodes and nodeclaims merged by providerID into StateNodes, pod→node
bindings, an anti-affinity pod index, nominations, MarkedForDeletion, and a
consolidation-state timestamp (`mark_unconsolidated`/`consolidation_state`,
cluster.go:310-337). `synced()` is the superset gate (cluster.go:85-127):
every apiserver NodeClaim/Node must be represented in memory before the
provisioner or the disruption controller may solve.

Events flow in through `on_event` (the informer layer,
state/informer/{pod,node,nodeclaim}.go collapsed into one method — our
hermetic runtime has a single watch stream).

New pod bindings and interruption notices also feed the fleet ledger's
causal node-lifecycle timeline (obs/timeline.py; deploy/README.md "Fleet
ledger") — ``bind`` and ``interrupt`` events on the bounded ring, the
latter counting the observed interruption-rate feed's notices.
"""

from __future__ import annotations

import collections
import itertools

from karpenter_tpu.api import labels as wk
from karpenter_tpu.state.statenode import StateNode
from karpenter_tpu.utils import pod as pod_util

_anon_counter = itertools.count(1)

# journal capacity: must cover every informer event between two disruption
# snapshot reads or the consumer sees a gap and rebuilds from scratch. A
# 1000-node consolidation wave generates ~4-5k events (pod deletes +
# recreates + binds + node/claim deletes) and a multi-round 2000-node
# convergence ~5k per ROUND — a 16k cap aged out mid-convergence and
# forced exactly the full re-tensorization the delta path exists to
# avoid (the fused round's tensorize lever; bench.py gates the wave at
# zero gap-rebuilds), so the default covers several such waves while
# still bounding memory to one deque of small tuples (~6 MB worst case).
DELTA_JOURNAL_CAP = 65536


def _journal_cap() -> int:
    from karpenter_tpu.utils.envknobs import env_int

    return env_int("KARPENTER_DELTA_JOURNAL_CAP", DELTA_JOURNAL_CAP,
                   minimum=1024)


def delta_to_wire(delta):
    """JSON-safe form of one journal entry for cross-process consumers (the
    solver fleet service's streaming delta protocol, service/
    solver_service.py): pods serialize to their uid — a wire consumer
    tracks rows and provenance, never live objects. ``None`` (the opaque
    entry) survives the trip as JSON null so the far side still knows it
    must resync."""
    if delta is None:
        return None
    if delta[0] == "node":
        return {"k": "node", "pid": delta[1]}
    _, pod, node_name, gone = delta
    return {
        "k": "pod",
        "uid": getattr(pod, "uid", str(pod)),
        "node": node_name,
        "gone": bool(gone),
    }


def delta_from_wire(obj):
    """Inverse of :func:`delta_to_wire` (pods come back as their uid)."""
    if obj is None:
        return None
    if obj.get("k") == "node":
        return ("node", obj["pid"])
    return ("pod", obj["uid"], obj.get("node"), bool(obj.get("gone")))


def _nodepool_sched_fingerprint(np_) -> tuple:
    """Everything on a NodePool that can change a scheduling or
    disruption answer, folded into one comparable value: the drift
    static-hash (template labels/annotations/taints/kubelet/class ref)
    plus the fields it deliberately excludes but the solver and the
    disruption ladder consume — template requirements and resource
    requests, weight, limits, the whole disruption block (policy,
    consolidate/expire windows, budgets), the status conditions
    (readiness gates which pools the provisioner solves over), and —
    only when the pool HAS limits — the aggregated usage itself
    (remaining = spec − usage feeds the solve). An event whose
    fingerprint is unchanged is status bookkeeping and must not bump
    the consolidation generation."""
    spec = np_.spec
    t = spec.template
    d = spec.disruption
    return (
        np_.static_hash(),
        repr(t.requirements),
        repr(t.resource_requests),
        spec.weight,
        repr(spec.limits),
        d.consolidation_policy,
        d.consolidate_after,
        d.expire_after,
        repr(d.budgets),
        tuple(
            (getattr(c, "type", None), getattr(c, "status", None))
            for c in np_.status.conditions
        ),
        repr(np_.status.resources) if spec.limits else None,
    )


class Cluster:
    def __init__(self, store, clock=None):
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.clock = clock or Clock()
        self._nodes: dict = {}  # provider_id -> StateNode
        self._node_name_to_pid: dict = {}  # node name -> provider_id
        self._claim_name_to_pid: dict = {}  # claim name -> provider_id
        self._bindings: dict = {}  # pod key -> node name
        self._antiaffinity_pods: dict = {}  # pod key -> Pod (bound, w/ required anti-affinity)
        self._state_seq: int = 0
        # structured delta journal: one entry per generation bump, consumed
        # by the disruption snapshot cache (ops/consolidate.py) to patch its
        # tensorized view instead of rebuilding. Entry = (seq, delta) where
        # delta is ("node", provider_id), ("pod", pod, node_name|None, gone)
        # or None (opaque: the consumer must rebuild from scratch).
        self._delta_journal: collections.deque = collections.deque(
            maxlen=_journal_cap()
        )
        # per-nodepool scheduling fingerprint (ISSUE 14): the counter
        # controller rewrites status.resources after every node wave, and
        # treating those bookkeeping writes as consolidation-relevant
        # re-opened the noop fence (and rebuilt the snapshot cache) once
        # per wave for nothing — only a fingerprint CHANGE bumps now
        self._np_fingerprints: dict = {}

    # -- informer entry point -------------------------------------------
    def on_event(self, event):
        kind, typ, obj = event.kind, event.type, event.obj
        if kind == "nodes":
            if typ == "Deleted":
                self.delete_node(obj)
            else:
                self.update_node(obj)
        elif kind == "nodeclaims":
            if typ == "Deleted":
                self.delete_node_claim(obj)
            else:
                self.update_node_claim(obj)
        elif kind == "pods":
            if typ == "Deleted":
                self.delete_pod(obj)
            else:
                self.update_pod(obj)
        elif kind == "nodepools":
            # a nodepool SPEC or readiness change can change the
            # consolidation answer (templates, requirements, budgets,
            # limits, weight — all feed the solver inputs the disruption
            # snapshot cache keys on this counter), so it bumps opaque.
            # A STATUS-only write with the scheduling fingerprint
            # unchanged — the counter controller refreshing
            # status.resources on a pool without limits after every node
            # wave — is bookkeeping: bumping for it re-opened the noop
            # fence and displaced the cached snapshot once per wave for
            # nothing. Usage still participates WHEN the pool has limits
            # (remaining = spec − usage feeds the solve).
            if typ == "Deleted":
                self._np_fingerprints.pop(obj.metadata.name, None)
                self.mark_unconsolidated()
            else:
                fp = _nodepool_sched_fingerprint(obj)
                if self._np_fingerprints.get(obj.metadata.name) != fp:
                    self._np_fingerprints[obj.metadata.name] = fp
                    self.mark_unconsolidated()
        elif kind == "daemonsets":
            # any daemonset change can change the consolidation answer
            # (daemon overhead rides the cached solver inputs)
            self.mark_unconsolidated()

    def resync(self):
        """Full rebuild from the store snapshot — leadership takeover: a
        fresh leader's informer cache must warm before it reconciles (the
        reference's client-go informers re-list on start; the hermetic
        store's event queue is single-consumer, so a standby that never
        drained catches up here)."""
        self._nodes.clear()
        self._node_name_to_pid.clear()
        self._claim_name_to_pid.clear()
        self._bindings.clear()
        self._antiaffinity_pods.clear()
        # fingerprints re-learn from the next events (a cleared entry can
        # only cause one extra opaque bump — the safe direction)
        self._np_fingerprints.clear()
        self.mark_unconsolidated()  # opaque: a rebuilt mirror has no delta
        for claim in self.store.list("nodeclaims"):
            self.update_node_claim(claim)
        for node in self.store.list("nodes"):
            self.update_node(node)
        for pod in self.store.list("pods"):
            self.update_pod(pod)

    # -- node / claim tracking (cluster.go UpdateNode/UpdateNodeClaim) ---
    def _state_for(self, provider_id: str) -> StateNode:
        if not provider_id:
            provider_id = f"anon-{next(_anon_counter)}"
        sn = self._nodes.get(provider_id)
        if sn is None:
            sn = StateNode(provider_id)
            self._nodes[provider_id] = sn
        return sn

    def update_node(self, node):
        pid = node.provider_id or node.name
        old_pid = self._node_name_to_pid.get(node.name)
        if old_pid is not None and old_pid != pid:
            old = self._nodes.get(old_pid)
            if old is not None:
                old.node = None
                self._gc(old_pid)
            self.mark_unconsolidated(("node", old_pid))
        sn = self._state_for(pid)
        sn.node = node
        self._node_name_to_pid[node.name] = pid
        self.mark_unconsolidated(("node", pid))
        return sn

    def delete_node(self, node):
        pid = self._node_name_to_pid.pop(node.name, None)
        if pid is None:
            return
        sn = self._nodes.get(pid)
        if sn is not None:
            sn.node = None
            self._gc(pid)
        self.mark_unconsolidated(("node", pid))

    def update_node_claim(self, claim):
        pid = claim.status.provider_id or claim.name
        old_pid = self._claim_name_to_pid.get(claim.name)
        if old_pid is not None and old_pid != pid:
            # claim gained its providerID: re-key (cluster.go updates by
            # provider id once launched)
            old = self._nodes.pop(old_pid, None)
            if old is not None:
                old.provider_id = pid
                existing = self._nodes.get(pid)
                if existing is not None:
                    existing.node_claim = claim
                    existing.marked_for_deletion |= old.marked_for_deletion
                else:
                    self._nodes[pid] = old
            self.mark_unconsolidated(("node", old_pid))
        sn = self._state_for(pid)
        sn.node_claim = claim
        self._claim_name_to_pid[claim.name] = pid
        self.mark_unconsolidated(("node", pid))
        return sn

    def delete_node_claim(self, claim):
        pid = self._claim_name_to_pid.pop(claim.name, None)
        if pid is None:
            return
        sn = self._nodes.get(pid)
        if sn is not None:
            sn.node_claim = None
            self._gc(pid)
        self.mark_unconsolidated(("node", pid))

    def _gc(self, pid: str):
        sn = self._nodes.get(pid)
        if sn is not None and sn.node is None and sn.node_claim is None:
            del self._nodes[pid]

    # -- pod tracking (cluster.go UpdatePod:284) -------------------------
    def update_pod(self, pod):
        key = pod.key()
        if pod_util.is_terminal(pod) or pod.metadata.deletion_timestamp is not None:
            self.delete_pod(pod)
            return
        bound = self._bindings.get(key)
        if bound is not None and bound != pod.node_name:
            self._unbind(key, bound)
            # the OLD node's usage changed too: journal it so the snapshot
            # cache rebuilds that row as well as the new binding's
            self.mark_unconsolidated(("pod", pod, bound, True))
            bound = None
        if pod.node_name and bound is None:
            self._bindings[key] = pod.node_name
            sn = self._node_by_name(pod.node_name)
            if sn is not None:
                sn.pods[key] = pod
                sn.host_port_usage.add(pod)
                sn.volume_usage.add(pod, kube=self.store)
            from karpenter_tpu.obs import timeline

            timeline.record_event("bind", pod.node_name, pod=key)
            if (
                pod.affinity
                and pod.affinity.pod_anti_affinity
                and pod.affinity.pod_anti_affinity.required
            ):
                self._antiaffinity_pods[key] = pod
        elif pod.node_name and bound == pod.node_name:
            sn = self._node_by_name(pod.node_name)
            if sn is not None:
                sn.pods[key] = pod  # refresh the stored object
        # EVERY non-delete pod event bumps the generation — a new binding,
        # a refreshed bound object (labels/tolerations/topology changes the
        # cached disruption snapshot tensorized from the old object), or an
        # unbound pending pod joining the counterfactual baseline. The
        # consolidation_state() contract makes this mandatory; keeping the
        # bump unconditional means a future branch cannot silently miss it.
        self.mark_unconsolidated(("pod", pod, pod.node_name or None, False))

    def delete_pod(self, pod):
        key = pod.key()
        bound = self._bindings.pop(key, None)
        if bound is not None:
            self._unbind(key, bound)
        self._antiaffinity_pods.pop(key, None)
        self.mark_unconsolidated(("pod", pod, bound, True))

    def _unbind(self, key: str, node_name: str):
        sn = self._node_by_name(node_name)
        if sn is not None:
            sn.pods.pop(key, None)
            sn.host_port_usage.remove(key)
            sn.volume_usage.remove(key)

    def _node_by_name(self, name: str):
        pid = self._node_name_to_pid.get(name)
        if pid is not None:
            return self._nodes.get(pid)
        # a claim whose node hasn't appeared yet may already carry the name
        for sn in self._nodes.values():
            if sn.name == name:
                return sn
        return None

    # -- views -----------------------------------------------------------
    def nodes(self) -> list:
        """Snapshot of all StateNodes (deep-enough copies; the scheduler and
        the disruption simulation mutate them, cluster.go Nodes())."""
        return [sn.snapshot() for sn in self._nodes.values()]

    def state_nodes(self):
        """The live (unsnapshotted) StateNodes — read-only iteration."""
        return self._nodes.values()

    def node_for(self, provider_id: str):
        return self._nodes.get(provider_id)

    def node_by_name(self, name: str):
        return self._node_by_name(name)

    def bound_node(self, pod) -> str | None:
        return self._bindings.get(pod.key())

    def pods_with_anti_affinity(self):
        for pod in self._antiaffinity_pods.values():
            node = self._node_by_name(pod.node_name)
            yield pod, (node.labels() if node is not None else {})

    # -- synced gate (cluster.go Synced:85) ------------------------------
    def synced(self) -> bool:
        for claim in self.store.list("nodeclaims"):
            if not claim.launched:
                continue  # nothing to mirror yet
            if claim.name not in self._claim_name_to_pid:
                return False
        for node in self.store.list("nodes"):
            if node.name not in self._node_name_to_pid:
                return False
        return True

    # -- nomination (cluster.go NominateNodeForPod) ----------------------
    def nominate(self, node_name: str):
        sn = self._node_by_name(node_name)
        if sn is not None:
            sn.nominate(self.clock.now())

    # -- interruption notices (spot resilience) --------------------------
    def note_interruption(self, provider_id: str, deadline: float) -> bool:
        """Mark a StateNode with its provider reclaim deadline (the
        disruption controller pulls notices from the cloud provider and
        lands them here). Journals a node-scoped delta — the cached
        disruption snapshot stays delta-advanceable — and bumps the
        consolidation generation so the round that must act re-probes.
        Idempotent per (node, deadline); False when the node is unknown
        (a notice for capacity we no longer track)."""
        sn = self._nodes.get(provider_id)
        if sn is None:
            return False
        if sn.interruption_deadline == deadline:
            return True
        sn.interruption_deadline = deadline
        self.mark_unconsolidated(("node", provider_id))
        labels = sn.labels()
        from karpenter_tpu.obs import timeline

        timeline.record_event(
            "interrupt", sn.name or provider_id, deadline=deadline,
            instance_type=labels.get(wk.INSTANCE_TYPE_LABEL, ""),
            zone=labels.get(wk.TOPOLOGY_ZONE_LABEL, ""))
        return True

    # -- deletion marks (cluster.go MarkForDeletion) ---------------------
    def mark_for_deletion(self, *provider_ids):
        for pid in provider_ids:
            sn = self._nodes.get(pid)
            if sn is not None:
                sn.marked_for_deletion = True
            self.mark_unconsolidated(("node", pid))
        if not provider_ids:
            self.mark_unconsolidated()

    def unmark_for_deletion(self, *provider_ids):
        for pid in provider_ids:
            sn = self._nodes.get(pid)
            if sn is not None:
                sn.marked_for_deletion = False
            self.mark_unconsolidated(("node", pid))
        if not provider_ids:
            self.mark_unconsolidated()

    # -- consolidation fence (cluster.go:310-337) ------------------------
    def mark_unconsolidated(self, delta=None) -> int:
        """Bump the state sequence. The reference uses a timestamp; a
        sequence number gives the same fencing under a fake clock.

        ``delta`` optionally journals a STRUCTURED description of what
        moved — ("node", provider_id) for any node/claim-scoped change,
        ("pod", pod, node_name|None, gone) for pod lifecycle — letting the
        disruption snapshot cache patch its tensorized view instead of
        rebuilding (ops/tensorize.py documents the delta contract). None
        journals an OPAQUE bump: consumers must treat the cached view as
        unreconstructible and rebuild. Passing no delta is therefore always
        safe, only slower."""
        self._state_seq += 1
        self._delta_journal.append((self._state_seq, delta))
        return self._state_seq

    def deltas_since(self, generation: int) -> list | None:
        """Journal entries for every bump in (generation, current], oldest
        first, or None when the journal no longer covers that range (entries
        aged out of the capped deque, or `generation` predates this process).
        A None return — like any None entry inside the list — means the
        consumer cannot patch and must rebuild."""
        if generation == self._state_seq:
            return []
        out = []
        for seq, delta in reversed(self._delta_journal):
            if seq <= generation:
                break
            out.append(delta)
        else:
            # walked off the journal without reaching `generation`: entries
            # between it and the oldest retained seq are lost
            if not self._delta_journal or self._delta_journal[0][0] != generation + 1:
                return None
        out.reverse()
        return out

    def export_deltas(self, generation: int) -> tuple:
        """``(wire_entries, current_generation)`` — the journal window since
        ``generation`` in the JSON-safe wire form (:func:`delta_to_wire`),
        for consumers on the far side of a process boundary. ``wire_entries``
        is None on a journal gap (entries aged out of the capped deque),
        mirroring :meth:`deltas_since`; an opaque in-process entry crosses
        as JSON null. The solver fleet service's session clients ship this
        window as the provenance of each delta round, and treat None / a
        null entry as their cue to resync with a full snapshot."""
        deltas = self.deltas_since(generation)
        if deltas is None:
            return None, self._state_seq
        return [delta_to_wire(d) for d in deltas], self._state_seq

    def consolidation_state(self) -> int:
        """Fence for consolidation decisions: if unchanged since the last
        fruitless consolidation round, nothing relevant moved and the
        search can be skipped (consolidation.go isConsolidated).

        This counter doubles as the GENERATION KEY of the disruption
        snapshot cache (ops/consolidate.py SnapshotCache): a tensorized
        cluster view is valid exactly as long as this value is unchanged,
        so every informer mutation that can change a scheduling answer
        must bump it."""
        return self._state_seq
