"""StateNode: the merged NodeClaim + Node view.

Behavioral mirror of the reference's pkg/controllers/state/statenode.go: a
single logical machine may be represented by a NodeClaim (in flight), a Node
(registered), or both. The scheduler consumes StateNodes as existing
capacity; the disruption controller consumes them as candidates. Key
semantics: `registered`/`initialized` (statenode.go:297-314), `available()`
= allocatable − pod requests (:350), taints drawn from the claim until the
node initializes, `nominate` with a TTL window (:392-398, :432), and
`validate_disruptable` (do-not-disrupt annotation + nodepool resolvability,
:174).
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.scheduling.hostports import HostPortUsage
from karpenter_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS
from karpenter_tpu.scheduling.volumes import VolumeUsage
from karpenter_tpu.utils import resources as resutil

# How long a nomination reserves in-flight capacity before the pod must have
# bound (the reference derives this from 2× the batch max duration,
# cluster.go nominationWindow).
NOMINATION_WINDOW = 20.0


class StateNode:
    def __init__(self, provider_id: str = ""):
        self.provider_id = provider_id
        self.node = None  # api.objects.Node | None
        self.node_claim = None  # api.nodeclaim.NodeClaim | None
        # pod bookkeeping (maintained by Cluster)
        self.pods: dict = {}  # pod key -> Pod (bound, non-terminal)
        self.host_port_usage = HostPortUsage()
        self.volume_usage = VolumeUsage()
        # disruption bookkeeping
        self.marked_for_deletion: bool = False
        self.nominated_until: float = 0.0
        # spot interruption notice: the provider's reclaim deadline (clock
        # seconds) or None. Set by Cluster.note_interruption when the
        # disruption controller pulls a notice; consumed by the
        # InterruptionDrain method (proactive drain-and-replace)
        self.interruption_deadline: float | None = None

    # -- identity --------------------------------------------------------
    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.name
        if self.node_claim is not None:
            return self.node_claim.status.node_name or self.node_claim.name
        return ""

    @property
    def hostname(self) -> str:
        if self.node is not None:
            return self.node.labels.get(wk.HOSTNAME_LABEL, self.node.name)
        return self.name

    def labels(self) -> dict:
        if self.node is not None:
            return self.node.labels
        if self.node_claim is not None:
            return self.node_claim.metadata.labels
        return {}

    def annotations(self) -> dict:
        out = {}
        if self.node_claim is not None:
            out.update(self.node_claim.metadata.annotations)
        if self.node is not None:
            out.update(self.node.metadata.annotations)
        return out

    @property
    def nodepool_name(self) -> str:
        return self.labels().get(wk.NODEPOOL_LABEL, "")

    def managed(self) -> bool:
        """Owned by a NodeClaim (vs. a bring-your-own node)."""
        return self.node_claim is not None or wk.NODEPOOL_LABEL in self.labels()

    # -- lifecycle gates (statenode.go:297-314) --------------------------
    def registered(self) -> bool:
        if self.node_claim is not None:
            return self.node_claim.registered
        return self.node is not None and self.node.labels.get(wk.NODE_REGISTERED_LABEL) == "true"

    def initialized(self) -> bool:
        if self.node_claim is not None:
            return self.node_claim.initialized
        return self.node is not None and self.node.labels.get(wk.NODE_INITIALIZED_LABEL) == "true"

    def deleting(self) -> bool:
        if self.node is not None and self.node.metadata.deletion_timestamp is not None:
            return True
        if self.node_claim is not None and self.node_claim.metadata.deletion_timestamp is not None:
            return True
        return False

    # -- capacity (statenode.go:340-360) ---------------------------------
    def capacity(self) -> dict:
        # trust the claim's view until the node has initialized: kubelet may
        # not have registered extended resources yet
        if self.node_claim is not None and not self.initialized():
            return dict(self.node_claim.status.capacity or {})
        if self.node is not None:
            return dict(self.node.capacity)
        if self.node_claim is not None:
            return dict(self.node_claim.status.capacity or {})
        return {}

    def allocatable(self) -> dict:
        if self.node_claim is not None and not self.initialized():
            return dict(self.node_claim.status.allocatable or {})
        if self.node is not None:
            return dict(self.node.allocatable)
        if self.node_claim is not None:
            return dict(self.node_claim.status.allocatable or {})
        return {}

    def pod_requests(self) -> dict:
        total: dict = {}
        for pod in self.pods.values():
            total = resutil.merge(total, pod.effective_requests())
        return total

    def daemonset_requests(self) -> dict:
        total: dict = {}
        for pod in self.pods.values():
            if pod.owned_by_daemonset():
                total = resutil.merge(total, pod.effective_requests())
        return total

    def available(self) -> dict:
        """Allocatable minus everything already placed (statenode.go:350)."""
        return resutil.subtract(self.allocatable(), self.pod_requests())

    # -- taints (statenode.go Taints) ------------------------------------
    def taints(self) -> list:
        if not self.initialized() and self.node_claim is not None:
            return list(self.node_claim.spec.taints)
        if self.node is not None:
            ephemeral = {t.key for t in KNOWN_EPHEMERAL_TAINTS}
            startup = (
                {t.key for t in self.node_claim.spec.startup_taints}
                if self.node_claim is not None
                else set()
            )
            return [t for t in self.node.taints if t.key not in ephemeral and t.key not in startup]
        return []

    # -- interruption (spot resilience) ----------------------------------
    def interruption_pending(self) -> bool:
        """A live interruption notice awaits action on this node: the
        deadline is set and the node is not already leaving. The ONE
        predicate shared by the disruption controller's round gate, the
        InterruptionDrain method's prewarm hint, and its candidate
        discovery — they must never disagree on what counts as noticed."""
        return (
            self.interruption_deadline is not None
            and not self.marked_for_deletion
            and not self.deleting()
        )

    # -- nomination (statenode.go:392-398) -------------------------------
    def nominate(self, now: float):
        self.nominated_until = now + NOMINATION_WINDOW

    def nominated(self, now: float) -> bool:
        return self.nominated_until > now

    # -- disruption gate (statenode.go ValidateDisruptable:174) ----------
    def validate_disruptable(self, pdb_limits=None) -> str | None:
        if self.annotations().get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true":
            return "disruption is blocked through the do-not-disrupt annotation"
        if not self.registered() or not self.initialized():
            return "node is not initialized"
        if not self.nodepool_name:
            return "node does not belong to a nodepool"
        for pod in self.pods.values():
            if pod.metadata.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true":
                return f"pod {pod.key()} has the do-not-disrupt annotation"
            if pdb_limits is not None:
                blocking = pdb_limits.can_evict(pod)
                if blocking is not None:
                    return f"pdb {blocking} prevents pod evictions"
        return None

    def reschedulable_pods(self) -> list:
        from karpenter_tpu.utils import pod as pod_util

        return [p for p in self.pods.values() if pod_util.is_reschedulable(p)]

    def snapshot(self) -> "StateNode":
        """Deep-enough copy for a scheduling simulation: the scheduler's
        ExistingNode wrapper mutates usage trackers, never the originals
        (the reference deep-copies StateNodes into each solve,
        cluster.go Nodes())."""
        out = StateNode(self.provider_id)
        out.node = self.node
        out.node_claim = self.node_claim
        out.pods = dict(self.pods)
        out.host_port_usage = self.host_port_usage.copy()
        out.volume_usage = self.volume_usage.copy()
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        out.interruption_deadline = self.interruption_deadline
        return out

    def __repr__(self):
        return (
            f"StateNode({self.name or self.provider_id}, claim={self.node_claim is not None}, "
            f"node={self.node is not None}, pods={len(self.pods)})"
        )
