"""Workload controller stand-in: replicasets + pod garbage collection.

The reference relies on the real controller-manager to recreate evicted
pods (deployments → replicasets) and to delete pods orphaned by node
deletion (pod GC). The hermetic cluster needs both for disruption to be
observable end-to-end: a drain evicts pods, this controller recreates them
as fresh pending pods, and the provisioner/binder land them on surviving or
replacement capacity.
"""

from __future__ import annotations

import itertools

_pod_seq = itertools.count(1)


class WorkloadController:
    def __init__(self, store):
        self.store = store

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = self._gc_orphans()
        # one pass over the pod list, indexed by owning deployment: the
        # naive per-deployment rescan is O(deployments × pods) and at fleet
        # scale (1000 deployments × 1000 pods) it dominated the whole
        # reconcile round — this is the controller-manager's informer-index
        # equivalent, not a behavior change (list order is preserved, so
        # scale-down still trims store-insertion order)
        owned_by: dict = {}
        for p in self.store.list("pods"):
            if p.metadata.deletion_timestamp is not None:
                continue
            for o in p.metadata.owner_references:
                if o.get("kind") == "Deployment":
                    owned_by.setdefault(
                        (p.metadata.namespace, o.get("name")), []
                    ).append(p)
        for deploy in self.store.list("deployments"):
            if deploy.template is None:
                continue
            owned = owned_by.get(
                (deploy.metadata.namespace, deploy.metadata.name), []
            )
            for extra in owned[deploy.replicas :]:
                # scale-down: newest-first would need creation ordering;
                # owned list order (store insertion) approximates it
                self.store.delete("pods", extra)
                progressed = True
            tmpl_sig = None
            for _ in range(deploy.replicas - len(owned)):
                p = deploy.template.clone()
                from karpenter_tpu.api.objects import new_uid

                p.metadata.name = f"{deploy.metadata.name}-{next(_pod_seq)}"
                p.metadata.namespace = deploy.metadata.namespace
                p.metadata.uid = new_uid("pod")
                p.metadata.owner_references = [
                    {"kind": "Deployment", "name": deploy.metadata.name, "controller": True}
                ]
                p.node_name = ""
                p.phase = "Pending"
                p.conditions = []
                # stamp the scheduling signature at index build time: every
                # replica of one deployment is spec-identical to its
                # template (the fields edited above — name/uid/owner/
                # node_name/phase/conditions — are not signature inputs,
                # and clone() deep-copies are value-equal), so the burst's
                # first tensorize pays ONE signature hash per deployment
                # instead of one per pod. Computed fresh per poll (not
                # memoized on the template object) so an edited template
                # stamps its NEW signature; already-running pods keep the
                # old spec and the old signature, which stays correct for
                # them. Solver-side clones drop the cache (dataclasses.
                # replace copies declared fields only), preserving the
                # relaxation-mutates-clones invariant.
                if tmpl_sig is None:
                    from karpenter_tpu.ops.tensorize import (
                        intern_signature,
                        pod_signature,
                    )

                    tmpl_sig = intern_signature(pod_signature(deploy.template))
                p.__dict__["_sig_cache"] = tmpl_sig
                self.store.create("pods", p)
                progressed = True
        return progressed

    def _gc_orphans(self) -> bool:
        """Delete pods bound to nodes that no longer exist (kube pod GC)."""
        progressed = False
        node_names = {n.name for n in self.store.list("nodes")}
        for p in list(self.store.list("pods")):
            if p.node_name and p.node_name not in node_names:
                if p.metadata.deletion_timestamp is None:
                    self.store.delete("pods", p)
                    progressed = True
        return progressed
