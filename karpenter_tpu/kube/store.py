"""In-memory apiserver: the envtest/kwok analog.

The reference's entire backend is client-go ↔ kube-apiserver (SURVEY.md §5
"distributed communication backend"): watch streams, finalizer-gated
deletion, the Eviction subresource, and leases. This store provides those
semantics in-process so the full controller ring runs hermetically — the
same role envtest (pkg/test/environment.go) plays for the reference's tier-1
suites and kwok for its e2e tier.

Semantics implemented:
- resourceVersion bump per mutation, with optimistic concurrency on
  update: a caller writing from a detached copy whose resourceVersion is
  stale gets ConflictError (apiserver 409). The synchronous controller
  ring aliases the stored instances — those writes always carry the
  current version — so today's controllers never conflict; the check
  guards any future concurrent worker or remote client
  (kube/client.py retry_on_conflict is the retry pattern)
- deletion with finalizers: delete stamps deletion_timestamp; the object
  disappears only when its finalizer list empties
- watch events queued per mutation, drained by the controller manager
- pod Eviction subresource honoring PDB disruptionsAllowed (429 analog)
- pod binding (pod.node_name immutable once set)
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from karpenter_tpu.api.objects import ObjectMeta, PodDisruptionBudget
from karpenter_tpu.kube.client import KubeClient


class NotFoundError(Exception):
    pass


class ConflictError(Exception):
    pass


class StaleVersionError(ConflictError):
    """Optimistic-concurrency conflict (apiserver 409 on a stale
    resourceVersion) — the only ConflictError a re-read can cure, and the
    only one retry_on_conflict retries (client-go retry.RetryOnConflict)."""


class TooManyRequests(Exception):
    """Eviction blocked by a PodDisruptionBudget (HTTP 429 analog)."""


@dataclass
class Event:
    kind: str
    type: str  # Added | Modified | Deleted
    obj: object = None


# kinds are plural lowercase, mirroring rest paths
KINDS = (
    "pods",
    "nodes",
    "nodepools",
    "nodeclaims",
    "daemonsets",
    "deployments",
    "pdbs",
    "pvcs",
    "pvs",
    "storageclasses",
    "volumeattachments",
    "namespaces",
    "leases",
    "events",
    "nodeclasses",
    "priorityclasses",
)

_NAMESPACED = {"pods", "daemonsets", "deployments", "pdbs", "pvcs", "leases", "events"}


def _key(kind: str, obj) -> str:
    meta = obj.metadata
    return f"{meta.namespace}/{meta.name}" if kind in _NAMESPACED else meta.name


class KubeStore(KubeClient):
    def __init__(self, clock=None):
        from karpenter_tpu.utils.clock import Clock

        self.clock = clock or Clock()
        self._objects: dict = {k: {} for k in KINDS}
        self._rv = 0
        self._events: list = []
        self._lock = threading.RLock()

    # -- core CRUD -------------------------------------------------------
    def create(self, kind: str, obj):
        from karpenter_tpu.api.admission import admit

        admit(kind, obj)  # webhook/CEL analog: reject illegal specs
        with self._lock:
            key = _key(kind, obj)
            if key in self._objects[kind]:
                raise ConflictError(f"{kind}/{key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self.clock.now()
            self._objects[kind][key] = obj
            self._events.append(Event(kind, "Added", obj))
            return obj

    def get(self, kind: str, name: str, namespace: str = "default"):
        with self._lock:
            key = f"{namespace}/{name}" if kind in _NAMESPACED else name
            obj = self._objects[kind].get(key)
            if obj is None:
                raise NotFoundError(f"{kind}/{key}")
            return obj

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def update(self, kind: str, obj):
        from karpenter_tpu.api.admission import admit

        admit(kind, obj)
        with self._lock:
            key = _key(kind, obj)
            stored = self._objects[kind].get(key)
            if stored is None:
                raise NotFoundError(f"{kind}/{key}")
            # optimistic concurrency (apiserver 409): a DETACHED copy must
            # carry the stored resourceVersion; the aliased instance is by
            # definition current
            if stored is not obj and obj.metadata.resource_version != (
                stored.metadata.resource_version
            ):
                raise StaleVersionError(
                    f"{kind}/{key}: stale resourceVersion "
                    f"{obj.metadata.resource_version} != {stored.metadata.resource_version}"
                )
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
            self._events.append(Event(kind, "Modified", obj))
            # finalizer-gated deletion completes on any update that empties
            # the finalizer list after deletion was requested
            self._maybe_finalize(kind, key, obj)
            return obj

    def delete(self, kind: str, obj_or_name, namespace: str = "default"):
        with self._lock:
            if isinstance(obj_or_name, str):
                obj = self.get(kind, obj_or_name, namespace)
            else:
                obj = obj_or_name
            key = _key(kind, obj)
            if key not in self._objects[kind]:
                raise NotFoundError(f"{kind}/{key}")
            if obj.metadata.deletion_timestamp is None:
                obj.metadata.deletion_timestamp = self.clock.now()
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._events.append(Event(kind, "Modified", obj))
            self._maybe_finalize(kind, key, obj)

    def _maybe_finalize(self, kind: str, key: str, obj):
        if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
            del self._objects[kind][key]
            self._events.append(Event(kind, "Deleted", obj))

    def list(self, kind: str, namespace: str | None = None, predicate=None) -> list:
        with self._lock:
            out = list(self._objects[kind].values())
        if namespace is not None:
            out = [o for o in out if o.metadata.namespace == namespace]
        if predicate is not None:
            out = [o for o in out if predicate(o)]
        return out

    # -- watch -----------------------------------------------------------
    def drain_events(self) -> list:
        with self._lock:
            events, self._events = self._events, []
            return events

    # -- pod subresources ------------------------------------------------
    def bind(self, pod, node_name: str):
        with self._lock:
            if pod.node_name and pod.node_name != node_name:
                raise ConflictError(f"pod {pod.key()} already bound to {pod.node_name}")
            pod.node_name = node_name
            pod.phase = "Running"
            self.update("pods", pod)

    def evict(self, pod):
        """Eviction subresource: PDB-gated delete (the reference's terminator
        drives this API, terminator/eviction.go:129-193)."""
        with self._lock:
            for pdb in self.list("pdbs", namespace=pod.namespace):
                if pdb.selector is not None and pdb.selector.matches(pod.metadata.labels):
                    if self._disruptions_allowed(pdb) <= 0:
                        raise TooManyRequests(
                            f"eviction of {pod.key()} blocked by pdb {pdb.metadata.name}"
                        )
            self.delete("pods", pod)

    def evict_wave(self, pods):
        """One PDB-checked eviction WAVE: the batched form of
        :meth:`evict` the drain orchestration uses (node termination
        drains whole command waves — thousands of pods — and per-pod
        ``evict`` recomputes every matching PDB's allowance from a full
        pod-list scan each time). Returns ``(evicted, blocked)`` lists.

        Semantics are EXACTLY sequential ``evict`` calls in ``pods``
        order: each pod's check sees every earlier deletion of the wave.
        The batching is pure memoization — a PDB's allowance is computed
        once and reused until a pod MATCHING that PDB is deleted (only a
        matching pod's deletion can move its counts), then lazily
        recomputed; the lock is held across the wave, so the PDB set
        itself cannot change mid-wave."""
        evicted, blocked = [], []
        with self._lock:
            pdbs_by_ns: dict = {}
            allowance: dict = {}  # (ns, pdb name) -> disruptions allowed
            for pod in pods:
                ns = pod.namespace
                pdbs = pdbs_by_ns.get(ns)
                if pdbs is None:
                    pdbs = pdbs_by_ns[ns] = [
                        pdb for pdb in self.list("pdbs", namespace=ns)
                        if pdb.selector is not None
                    ]
                matching = [
                    pdb for pdb in pdbs
                    if pdb.selector.matches(pod.metadata.labels)
                ]
                allowed = True
                for pdb in matching:
                    key = (ns, pdb.metadata.name)
                    a = allowance.get(key)
                    if a is None:
                        a = allowance[key] = self._disruptions_allowed(pdb)
                    if a <= 0:
                        allowed = False
                        break
                if not allowed:
                    blocked.append(pod)
                    continue
                self.delete("pods", pod)
                for pdb in matching:
                    # a matching pod left the pod set: the memoized
                    # allowance is stale — recompute on next sight
                    allowance.pop((ns, pdb.metadata.name), None)
                evicted.append(pod)
        return evicted, blocked

    def _disruptions_allowed(self, pdb: PodDisruptionBudget) -> int:
        pods = [
            p
            for p in self.list("pods", namespace=pdb.metadata.namespace)
            if pdb.selector.matches(p.metadata.labels) and p.metadata.deletion_timestamp is None
        ]
        healthy = sum(1 for p in pods if p.phase == "Running")
        if pdb.min_available is not None:
            min_avail = _resolve_count(pdb.min_available, len(pods))
            return max(healthy - min_avail, 0)
        if pdb.max_unavailable is not None:
            max_unavail = _resolve_count(pdb.max_unavailable, len(pods))
            unhealthy = len(pods) - healthy
            return max(max_unavail - unhealthy, 0)
        return 1 << 30

    # -- convenience for the volume layer --------------------------------
    def get_pvc(self, namespace: str, name: str):
        return self.try_get("pvcs", name, namespace)

    def get_storage_class(self, name: str):
        return self.try_get("storageclasses", name) if name else None

    def get_pv(self, name: str):
        return self.try_get("pvs", name) if name else None


def _resolve_count(value, total: int) -> int:
    s = str(value)
    if s.endswith("%"):
        import math

        return int(math.ceil(total * float(s[:-1]) / 100.0))
    return int(s)
