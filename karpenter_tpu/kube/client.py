"""The client seam: the store surface controllers are allowed to touch.

The reference's controllers speak client-go's `client.Client` interface, not
etcd (operator.go:141; pkg/test/cachesyncingclient.go wraps the same seam
for tests). This module is our equivalent contract: `KubeClient` names every
operation a controller may perform, `KubeStore` (kube/store.py) is the
in-memory implementation, and anything that one day fronts a real
kube-apiserver implements the same surface — controllers never depend on
store internals.

Optimistic concurrency: `update` raises `ConflictError` when the caller's
object carries a stale resourceVersion (apiserver 409 semantics). The
synchronous controller ring aliases stored instances — those writes always
carry the current version — but any caller working from a snapshot copy
(a future concurrent worker, a remote client) conflicts and must re-read;
`retry_on_conflict` packages the standard re-read-and-reapply loop
(client-go's retry.RetryOnConflict)."""

from __future__ import annotations


class KubeClient:
    """Abstract store surface (client-go client.Client analog)."""

    # -- CRUD ------------------------------------------------------------
    def create(self, kind: str, obj):
        raise NotImplementedError

    def get(self, kind: str, name: str, namespace: str = "default"):
        raise NotImplementedError

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        raise NotImplementedError

    def update(self, kind: str, obj):
        raise NotImplementedError

    def delete(self, kind: str, obj_or_name, namespace: str = "default"):
        raise NotImplementedError

    def list(self, kind: str, namespace: str | None = None, predicate=None) -> list:
        raise NotImplementedError

    # -- watch -----------------------------------------------------------
    def drain_events(self) -> list:
        raise NotImplementedError

    # -- pod subresources ------------------------------------------------
    def bind(self, pod, node_name: str):
        raise NotImplementedError

    def evict(self, pod):
        raise NotImplementedError

    # -- volume resolution (scheduling/volumes.py consumers) -------------
    def get_pvc(self, namespace: str, name: str):
        raise NotImplementedError

    def get_storage_class(self, name: str):
        raise NotImplementedError

    def get_pv(self, name: str):
        raise NotImplementedError


def retry_on_conflict(fn, attempts: int = 5):
    """Run `fn()` retrying on StaleVersionError — the caller's fn must
    re-read the object each attempt (client-go retry.RetryOnConflict).
    Other ConflictErrors (create of an existing key, double bind) are not
    retried: no re-read can cure them."""
    from karpenter_tpu.kube.store import StaleVersionError

    last = None
    for _ in range(attempts):
        try:
            return fn()
        except StaleVersionError as e:
            last = e
    raise last
