"""DaemonSet controller stand-in for the hermetic cluster.

The reference's envtest has no controller-manager, so daemon pods never
materialize there either — but our end-to-end loop models node capacity
consumption, and daemonset overhead is only real if daemon pods actually
occupy nodes. This stamps one pod per (daemonset, eligible node), bound
directly, the way the real daemonset controller + default scheduler would.
Eligibility mirrors the provisioner's overhead filter: tolerates the node's
taints and the node's labels satisfy the template's requirements.
"""

from __future__ import annotations

from karpenter_tpu.scheduling import daemon_schedulable, label_requirements


class DaemonSetController:
    def __init__(self, store):
        self.store = store

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        from karpenter_tpu.utils import resources as resutil

        progressed = False
        nodes = [
            n
            for n in self.store.list("nodes")
            if n.ready and n.metadata.deletion_timestamp is None
        ]
        # remaining capacity per node: daemon pods only land where they fit
        used: dict = {n.name: {} for n in nodes}
        for p in self.store.list("pods"):
            if p.node_name in used and p.metadata.deletion_timestamp is None:
                used[p.node_name] = resutil.merge(used[p.node_name], p.effective_requests())
        for ds in self.store.list("daemonsets"):
            if ds.template is None:
                continue
            for node in nodes:
                name = f"{ds.metadata.name}-{node.name}"
                if self.store.try_get("pods", name, ds.metadata.namespace) is not None:
                    continue
                tmpl = ds.template
                if not daemon_schedulable(tmpl, node.taints, label_requirements(node.labels)):
                    continue
                free = resutil.subtract(node.allocatable, used[node.name])
                if not resutil.fits(tmpl.effective_requests(), free):
                    continue  # would overcommit: the real scheduler leaves it Pending
                p = tmpl.clone()
                p.metadata.name = name
                p.metadata.namespace = ds.metadata.namespace
                from karpenter_tpu.api.objects import new_uid

                p.metadata.uid = new_uid("dspod")
                p.metadata.owner_references = [
                    {"kind": "DaemonSet", "name": ds.metadata.name, "controller": True}
                ]
                self.store.create("pods", p)
                self.store.bind(p, node.name)
                used[node.name] = resutil.merge(used[node.name], p.effective_requests())
                progressed = True
        return progressed
