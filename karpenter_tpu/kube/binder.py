"""Minimal kube-scheduler stand-in for the hermetic cluster.

The reference relies on the real kube-scheduler to bind pods onto nodes it
launches (SURVEY.md §3.2 final step); its tests bind manually
(expectations.go ExpectProvisioned:276). Our in-memory cluster needs an
actual binder so the end-to-end loop closes: pending pods land on ready,
compatible nodes — preferring the node they were nominated onto — and pods
the binder cannot place get the Unschedulable condition the provisioner
watches for.
"""

from __future__ import annotations

import time

from karpenter_tpu.scheduling import Taints, label_requirements, pod_requirements
from karpenter_tpu.api import labels as wk
from karpenter_tpu.utils import pod as pod_util
from karpenter_tpu.utils import resources as resutil

# process-wide binding accounting, delta'd by `python -m perf global`
# (the rebind_ms half of the post-command wave's breakdown)
STATS = {
    "rebind_ms": 0.0,
    "passes": 0,
    "bound": 0,
    "hinted": 0,  # binds landed via a consolidation wave hint
}

# Wave hints: node name -> displaced-pod count the consolidation round's
# displacement plan routed there (ops/consolidate.py JointPlan
# .displacement, seeded by the disruption controller post-confirm).
# Evicted pods are deleted and re-created by the workload controller, so
# hints key by TARGET NODE, not pod identity: the binder tries hinted
# survivors first and lets ``_fits`` validate — a stale or wrong hint
# falls through to the normal cursor scan, costing nothing but the one
# check. Consumption is destructive (counts decrement per bind) so a
# hint never outlives its wave. This is the device-side rebinding lever
# of the fused cluster round (deploy/README.md "Fused cluster round").
WAVE_HINTS: dict = {}


def seed_wave_hints(entries) -> int:
    """Merge ``(node_name, count)`` pairs into the wave-hint table;
    returns the number of hinted slots now outstanding."""
    for name, count in entries:
        if count > 0:
            WAVE_HINTS[name] = WAVE_HINTS.get(name, 0) + int(count)
    return sum(WAVE_HINTS.values())


def _shape_key(pod, pod_req) -> tuple:
    """Binding-equivalence key: two pods with the same key see the same
    ``_fits`` answer on every node (requests, tolerations, and the
    node_selector/affinity that ``pod_requirements`` reads). Affinity
    groups by object identity — clone-stamped replicas share their spec
    sub-objects by reference, so the deployment wave (the case the cursor
    exists for) collapses to one key, while structurally-equal-but-
    distinct affinities merely get their own cursor (correct, just less
    shared)."""
    return (
        tuple(sorted(pod_req.items())),
        tuple(sorted((pod.node_selector or {}).items())),
        tuple((t.key, t.operator, t.value, t.effect)
              for t in pod.tolerations),
        id(pod.affinity) if pod.affinity is not None else None,
    )


class Binder:
    _hint_hit = None  # node the last successful _try_hints landed on

    def __init__(self, store, clock=None, registry=None):
        from karpenter_tpu.operator import metrics as _m
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.clock = clock or Clock()
        self.registry = registry or _m.REGISTRY

    def _fits(self, pod, node, available: dict, node_view: dict,
              pod_req, pod_reqs) -> bool:
        if not node.ready or node.unschedulable or node.metadata.deletion_timestamp:
            return False
        view = node_view.get(node.name)
        if view is None:
            # per-pass memo: requirement/taint objects are per NODE, but
            # the scan tests every (pod, node) pair — rebuilding them per
            # pair made the binder O(pods × nodes × labels) and dominated
            # fleet-scale benches after a consolidation wave
            view = node_view[node.name] = (
                Taints(t for t in node.taints
                       if t.effect in ("NoSchedule", "NoExecute")),
                label_requirements(node.labels),
            )
        taints, node_reqs = view
        if taints.tolerates(pod):
            return False
        if node_reqs.compatible(pod_reqs, allow_undefined=wk.WELL_KNOWN_LABELS):
            return False
        return resutil.fits(pod_req, available[node.name])

    def bind_pending(self) -> int:
        """One binding pass; returns the number of pods progressed."""
        pending = [
            p
            for p in self.store.list("pods")
            if not p.node_name and p.metadata.deletion_timestamp is None
        ]
        if not pending:
            return 0  # idle tick: no trace, no work
        # a binding pass is the root of its own reconcile round (obs
        # flight recorder) — the scheduler stand-in's analog of the
        # provisioner's solve round
        from karpenter_tpu import obs

        t0 = time.perf_counter()
        with obs.round_trace("bind", registry=self.registry,
                             pending=len(pending)):
            progressed = self._bind(pending)
        STATS["rebind_ms"] += (time.perf_counter() - t0) * 1000.0
        STATS["passes"] += 1
        STATS["bound"] += progressed
        return progressed

    def _try_hints(self, pod, nodes, available, node_view, pod_req,
                   pod_reqs) -> bool:
        """Hint-first placement: try the CURRENT head of the wave-hint
        table before the cursor scan — at most one extra ``_fits`` check
        per pod, so a wave of wrong hints can never cost more than one
        probe each (the cursor scan below stays the ground truth and
        keeps its O(pods + nodes)-per-shape bound). A hit consumes one
        hinted slot (destructive); a miss rotates the head to the back so
        one cold node cannot shadow the rest of the wave's hints."""
        while WAVE_HINTS:
            hname = next(iter(WAVE_HINTS))
            hnode = nodes.get(hname)
            if hnode is None:
                del WAVE_HINTS[hname]  # node retired mid-wave: hint dead
                continue
            if self._fits(pod, hnode, available, node_view, pod_req,
                          pod_reqs):
                WAVE_HINTS[hname] -= 1
                if WAVE_HINTS[hname] <= 0:
                    del WAVE_HINTS[hname]
                STATS["hinted"] += 1
                self._hint_hit = hnode
                return True
            # rotate: re-insert at the back (dicts preserve order)
            WAVE_HINTS[hname] = WAVE_HINTS.pop(hname)
            return False
        return False

    def _bind(self, pending: list) -> int:
        from karpenter_tpu import obs

        progressed = 0
        node_view: dict = {}  # node name -> (Taints, label Requirements)
        with obs.span("bind.availability"):
            nodes = {n.name: n for n in self.store.list("nodes")}
            # availability computed once per pass, decremented as pods bind
            used: dict = {name: {} for name in nodes}
            for p in self.store.list("pods"):
                if p.node_name in used and p.metadata.deletion_timestamp is None:
                    used[p.node_name] = resutil.merge(
                        used[p.node_name], p.effective_requests())
            available = {
                name: resutil.subtract(nodes[name].allocatable, used[name])
                for name in nodes
            }

        # nominated pods get first crack at their reserved capacity
        pending.sort(key=lambda p: not p.nominated_node_name)
        node_order = list(nodes.values())
        # per-shape scan cursor: within one pass, availability only ever
        # DECREASES, and a node's taints/labels are fixed — so a node that
        # refused a pod can never accept a spec-identical pod later in the
        # same pass. Remembering, per pod shape, how far the scan has
        # proven the node order infeasible turns a consolidation wave
        # (thousands of clone-stamped replicas re-binding at once) from
        # O(pods × nodes) into O(pods + nodes) per shape — the scan that
        # dominated the 2k-node global-consolidation bench.
        cursor: dict = {}
        for pod in pending:
            placed = False
            # pod-side objects built once per pod, not once per (pod, node)
            pod_req = pod.effective_requests()
            pod_reqs = pod_requirements(pod)
            nominated = nodes.get(pod.nominated_node_name)
            if nominated is not None and self._fits(
                    pod, nominated, available, node_view, pod_req, pod_reqs):
                placed = True
                node = nominated
            elif WAVE_HINTS and self._try_hints(
                    pod, nodes, available, node_view, pod_req, pod_reqs):
                placed = True
                node = self._hint_hit
            else:
                key = _shape_key(pod, pod_req)
                start = cursor.get(key, 0)
                for i in range(start, len(node_order)):
                    node = node_order[i]
                    if node is nominated:
                        continue
                    if self._fits(pod, node, available, node_view, pod_req,
                                  pod_reqs):
                        # the node may still have room: same-shape scans
                        # resume HERE, not past it
                        cursor[key] = i
                        placed = True
                        break
                else:
                    cursor[key] = len(node_order)
            if placed:
                self.store.bind(pod, node.name)
                available[node.name] = resutil.subtract(
                    available[node.name], pod_req
                )
                # creation → bound latency (the reference's pod startup
                # duration summary, controllers/metrics/pod)
                if pod.metadata.creation_timestamp:
                    from karpenter_tpu.operator import metrics as m

                    self.registry.histogram(
                        m.PODS_STARTUP_DURATION,
                        "seconds from pod creation to binding",
                    ).observe(self.clock.now() - pod.metadata.creation_timestamp)
                progressed += 1
                continue
            target = nominated
            if (
                target is not None
                and target.ready
                and target.metadata.deletion_timestamp is None
                and not any(t.key == wk.UNREGISTERED_TAINT_KEY for t in target.taints)
            ):
                # nominated node is settled but can no longer take the pod
                # (capacity stolen) — drop the dead nomination so the
                # provisioner re-solves
                pod.nominated_node_name = ""
                self.store.update("pods", pod)
                progressed += 1
            elif not pod_util.failed_to_schedule(pod):
                # mark Unschedulable like the real scheduler would — this is
                # the condition the provisioner watches for
                pod.conditions.append(
                    {"type": "PodScheduled", "status": "False", "reason": "Unschedulable"}
                )
                self.store.update("pods", pod)
                progressed += 1
        return progressed
