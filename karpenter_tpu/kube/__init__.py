from karpenter_tpu.kube.store import KubeStore, Event, ConflictError, NotFoundError, TooManyRequests  # noqa: F401
from karpenter_tpu.kube.binder import Binder  # noqa: F401

__all__ = [
    "KubeStore", "Event", "ConflictError", "NotFoundError",
    "TooManyRequests", "Binder",
]
