"""CSI volume-count tracking vs per-driver node limits.

Semantics from the reference's pkg/scheduling/volumeusage.go:45-220: resolve
each pod PVC to its storage-class provisioner (driver), count distinct
volumes per driver per node, and reject adds that would exceed the driver's
volume-attach limit on that node.
"""

from __future__ import annotations


class VolumeUsage:
    def __init__(self):
        self._by_driver: dict = {}  # driver -> set of volume ids
        self._by_pod: dict = {}  # pod key -> [(driver, volume_id)]

    @staticmethod
    def pod_volumes(pod, kube=None) -> list:
        """Resolve pod PVC refs → (driver, volume_id) via the cluster's
        PVC/StorageClass objects when a kube view is provided."""
        out = []
        for v in getattr(pod, "volumes", None) or []:
            claim = getattr(v, "claim_name", None) or (v if isinstance(v, str) else None)
            if claim is None:
                continue
            driver, vol_id = "", f"{pod.namespace}/{claim}"
            if kube is not None:
                pvc = kube.get_pvc(pod.namespace, claim)
                if pvc is not None:
                    sc = kube.get_storage_class(getattr(pvc, "storage_class_name", ""))
                    driver = getattr(sc, "provisioner", "") if sc is not None else ""
                    vol_id = getattr(pvc, "volume_name", "") or vol_id
            out.append((driver, vol_id))
        return out

    def exceeds(self, pod, limits: dict, kube=None) -> str | None:
        """Error if adding the pod would exceed any driver limit on the node
        (limits: driver -> max volumes; missing driver = unlimited)."""
        if not limits:
            return None
        additions: dict = {}
        for driver, vol in self.pod_volumes(pod, kube):
            if vol not in self._by_driver.get(driver, ()):  # new distinct volume
                additions[driver] = additions.get(driver, 0) + 1
        for driver, extra in additions.items():
            if driver in limits:
                used = len(self._by_driver.get(driver, ()))
                if used + extra > limits[driver]:
                    return f"would exceed volume limit for driver {driver} ({used}+{extra}>{limits[driver]})"
        return None

    def add(self, pod, kube=None):
        vols = self.pod_volumes(pod, kube)
        if not vols:
            # volume-less pods count nothing: an empty entry only bloats
            # every snapshot/fork copy to O(pods-on-node)
            self._by_pod.pop(pod.key(), None)
            return
        self._by_pod[pod.key()] = vols
        for driver, vol in vols:
            self._by_driver.setdefault(driver, set()).add(vol)

    def remove(self, pod_key: str):
        # Rebuild per-driver sets from the remaining pods: a PVC shared by
        # several pods must stay counted while any referent remains
        # (volumeusage.go DeletePod recomputes for exactly this case).
        if self._by_pod.pop(pod_key, None) is None:
            return
        rebuilt: dict = {}
        for vols in self._by_pod.values():
            for driver, vol in vols:
                rebuilt.setdefault(driver, set()).add(vol)
        self._by_driver = rebuilt

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage()
        out._by_driver = {k: set(v) for k, v in self._by_driver.items()}
        out._by_pod = {k: list(v) for k, v in self._by_pod.items()}
        return out
