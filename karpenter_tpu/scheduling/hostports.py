"""Host-port conflict tracking per simulated node.

Semantics from the reference's pkg/scheduling/hostportusage.go:34-90: two
hostPort reservations conflict when protocols match and (ip overlap) and
port equality; 0.0.0.0 overlaps every ip.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class HostPort:
    ip: str
    port: int
    protocol: str = "TCP"

    def conflicts(self, other: "HostPort") -> bool:
        if self.protocol != other.protocol or self.port != other.port:
            return False
        if self.ip == "0.0.0.0" or other.ip == "0.0.0.0" or self.ip == "" or other.ip == "":
            return True
        return self.ip == other.ip


def pod_host_ports(pod) -> list:
    out = []
    for hp in getattr(pod, "host_ports", None) or []:
        if isinstance(hp, HostPort):
            out.append(hp)
        elif isinstance(hp, (tuple, list)):
            ip, port, *rest = hp
            out.append(HostPort(ip=ip or "0.0.0.0", port=int(port), protocol=rest[0] if rest else "TCP"))
        else:
            out.append(HostPort(ip="0.0.0.0", port=int(hp)))
    for c in getattr(pod, "containers", None) or []:
        for p in c.get("ports", []) or []:
            if p.get("hostPort"):
                out.append(
                    HostPort(
                        ip=p.get("hostIP") or "0.0.0.0",
                        port=int(p["hostPort"]),
                        protocol=p.get("protocol", "TCP"),
                    )
                )
    return out


class HostPortUsage:
    """Per-node in-use host ports (hostportusage.go:34)."""

    def __init__(self):
        self._by_pod: dict = {}  # pod key -> [HostPort]

    def conflicts(self, pod, ports=None) -> str | None:
        ports = pod_host_ports(pod) if ports is None else ports
        for owner, used in self._by_pod.items():
            for u in used:
                for p in ports:
                    if p.conflicts(u):
                        return f"port {p.port}/{p.protocol} in use by pod {owner}"
        return None

    def add(self, pod):
        ports = pod_host_ports(pod)
        if not ports:
            # a port-less pod reserves nothing: storing its empty entry
            # only bloats every snapshot/fork copy to O(pods-on-node)
            self._by_pod.pop(pod.key(), None)
            return
        self._by_pod[pod.key()] = ports

    def remove(self, pod_key: str):
        self._by_pod.pop(pod_key, None)

    def copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        out._by_pod = {k: list(v) for k, v in self._by_pod.items()}
        return out
