"""Taint/toleration checks (reference pkg/scheduling/taints.go)."""

from __future__ import annotations

from karpenter_tpu.api.objects import Taint

# Taints expected to be transient during node startup (taints.go KnownEphemeralTaints)
KNOWN_EPHEMERAL_TAINTS = (
    Taint(key="node.kubernetes.io/not-ready", effect="NoSchedule"),
    Taint(key="node.kubernetes.io/unreachable", effect="NoSchedule"),
    Taint(key="node.cloudprovider.kubernetes.io/uninitialized", value="true", effect="NoSchedule"),
)


class Taints(list):
    """Decorated list of Taint (taints.go:38)."""

    def tolerates(self, pod) -> str | None:
        """None if the pod tolerates every taint, else an error string
        (taints.go Tolerates:41)."""
        errs = []
        for taint in self:
            if not any(t.tolerates(taint) for t in pod.tolerations):
                errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
        return "; ".join(errs) if errs else None

    def merge(self, other) -> "Taints":
        """Union keeping self's entries on (key, effect) conflicts
        (taints.go Merge:56)."""
        out = Taints(self)
        for taint in other:
            if not any(taint.matches(t) for t in out):
                out.append(taint)
        return out
