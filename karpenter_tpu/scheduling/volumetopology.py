"""Volume topology injection: PV/StorageClass zone pins become pod
node-affinity requirements.

Mirror of the reference's pkg/controllers/provisioning/scheduling/
volumetopology.go:42-152: a pod mounting a PVC bound to a zonal PV must
schedule into that zone; an unbound PVC whose StorageClass restricts
AllowedTopologies must land where the volume can be provisioned. The
derived requirements are appended to EVERY required node-selector term so
they AND with existing constraints and survive preference relaxation.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import (
    Affinity,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
)


class PVCError(Exception):
    """Pod references a PVC that can't be resolved (validatePVCs,
    volumetopology.go:155)."""


class VolumeTopology:
    def __init__(self, kube):
        self.kube = kube

    # -- derive (getRequirements, volumetopology.go:81) ------------------
    def requirements_for(self, pod) -> list:
        out = []
        for v in getattr(pod, "volumes", None) or []:
            claim = getattr(v, "claim_name", None) or (v if isinstance(v, str) else None)
            if claim is None:
                continue  # emptyDir/hostPath etc. carry no PVC
            pvc = self.kube.get_pvc(pod.namespace, claim)
            if pvc is None:
                continue  # validation (below) reports this separately
            if pvc.volume_name:
                out.extend(self._pv_requirements(pvc.volume_name))
            elif pvc.storage_class_name:
                out.extend(self._storage_class_requirements(pvc.storage_class_name))
        return out

    def _pv_requirements(self, volume_name: str) -> list:
        pv = self.kube.get_pv(volume_name)
        if pv is None or not pv.node_affinity_required:
            return []
        # terms are ORed; mirror the reference in using only the first
        reqs = list(pv.node_affinity_required[0].match_expressions)
        if pv.local:
            # a Local/HostPath PV's hostname pin is void on reschedule
            reqs = [r for r in reqs if r.key != wk.HOSTNAME_LABEL]
        return reqs

    def _storage_class_requirements(self, name: str) -> list:
        sc = self.kube.get_storage_class(name)
        if sc is None or not sc.allowed_topologies:
            return []
        first = sc.allowed_topologies[0]
        return [
            NodeSelectorRequirement(t["key"], "In", list(t["values"]))
            for t in first.get("match_label_expressions", [])
        ]

    # -- inject (volumetopology.go:42) -----------------------------------
    def inject(self, pod) -> None:
        reqs = self.requirements_for(pod)
        if not reqs:
            return
        if pod.affinity is None:
            pod.affinity = Affinity()
        if pod.affinity.node_affinity is None:
            pod.affinity.node_affinity = NodeAffinity()
        na = pod.affinity.node_affinity
        if not na.required:
            na.required = [NodeSelectorTerm()]
        # AND into every ORed term so relaxation can't drop the volume pin
        for term in na.required:
            term.match_expressions = list(term.match_expressions) + list(reqs)

    # -- validate (ValidatePersistentVolumeClaims) -----------------------
    def validate(self, pod) -> None:
        for v in getattr(pod, "volumes", None) or []:
            claim = getattr(v, "claim_name", None) or (v if isinstance(v, str) else None)
            if claim is None:
                continue
            pvc = self.kube.get_pvc(pod.namespace, claim)
            if pvc is None:
                raise PVCError(f"pvc {pod.namespace}/{claim} not found")
            if pvc.volume_name:
                if self.kube.get_pv(pvc.volume_name) is None:
                    raise PVCError(f"pv {pvc.volume_name} not found")
            elif pvc.storage_class_name:
                if self.kube.get_storage_class(pvc.storage_class_name) is None:
                    raise PVCError(
                        f"storageclass {pvc.storage_class_name} not found")
