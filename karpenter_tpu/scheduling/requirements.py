"""Label-requirement set algebra.

Behavioral mirror of the reference's pkg/scheduling/requirement.go:33-188 and
requirements.go:36-253: a Requirement is a value set with an optional
complement flag (NotIn/Exists are complements), integer bounds for Gt/Lt, and
a minValues flexibility floor; Requirements is a key-indexed conjunction with
one-way `compatible` (undefined custom labels deny, undefined well-known
labels allow) and two-way `intersects`.

This algebra is also the host-side reference semantics for the device
tensorization (ops/tensorize.py) which lowers concrete (non-complement)
requirements to bitmasks over interned value vocabularies.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import NodeSelectorRequirement, sort_terms_by_weight

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_INF = 1 << 62  # stands in for "all possible values" when complemented


def _within(value: str, gt: int | None, lt: int | None) -> bool:
    if gt is None and lt is None:
        return True
    try:
        v = int(value)
    except (TypeError, ValueError):
        return False
    if gt is not None and v <= gt:
        return False
    if lt is not None and v >= lt:
        return False
    return True


class Requirement:
    """One label-key constraint (requirement.go:33)."""

    __slots__ = ("key", "complement", "values", "greater_than", "less_than", "min_values")

    def __init__(self, key: str, operator: str, values=(), min_values: int | None = None):
        key = wk.normalize(key)
        self.key = key
        self.min_values = min_values
        self.greater_than: int | None = None
        self.less_than: int | None = None
        if operator == IN:
            self.complement = False
            self.values = frozenset(values)
        elif operator == DOES_NOT_EXIST:
            self.complement = False
            self.values = frozenset()
        else:
            self.complement = True
            self.values = frozenset(values) if operator == NOT_IN else frozenset()
            if operator == GT:
                self.greater_than = int(next(iter(values)))
            elif operator == LT:
                self.less_than = int(next(iter(values)))

    @classmethod
    def _raw(cls, key, complement, values, gt=None, lt=None, min_values=None) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.complement = complement
        r.values = frozenset(values)
        r.greater_than = gt
        r.less_than = lt
        r.min_values = min_values
        return r

    @property
    def operator(self) -> str:
        if self.complement:
            return NOT_IN if self.values else EXISTS  # Gt/Lt report Exists-with-bounds
        return IN if self.values else DOES_NOT_EXIST

    def intersection(self, other: "Requirement") -> "Requirement":
        """requirement.go Intersection semantics, including bound collapse."""
        complement = self.complement and other.complement
        gt = _max_opt(self.greater_than, other.greater_than)
        lt = _min_opt(self.less_than, other.less_than)
        mv = _max_opt(self.min_values, other.min_values)
        if gt is not None and lt is not None and gt >= lt:
            return Requirement._raw(self.key, False, (), min_values=mv)
        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement:
            values = other.values - self.values
        elif other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = frozenset(v for v in values if _within(v, gt, lt))
        if not complement:
            gt, lt = None, None
        return Requirement._raw(self.key, complement, values, gt, lt, mv)

    def has(self, value: str) -> bool:
        if self.complement:
            return value not in self.values and _within(value, self.greater_than, self.less_than)
        return value in self.values and _within(value, self.greater_than, self.less_than)

    def __len__(self) -> int:
        if self.complement:
            return _INF - len(self.values)
        return len(self.values)

    def any(self) -> str:
        """A representative allowed value (requirement.go Any).

        Deviation from the reference: for unbounded complement requirements
        (NotIn/Exists with no Gt/Lt) the reference fabricates a random
        integer; we return "" so Labels() never stamps fabricated values.
        Bounded requirements still yield a valid in-range value.
        """
        if not self.complement and self.values:
            return sorted(self.values)[0]
        if self.complement and (self.greater_than is not None or self.less_than is not None):
            lo = (self.greater_than + 1) if self.greater_than is not None else 0
            hi = self.less_than if self.less_than is not None else lo + 1_000_000
            for cand in range(lo, hi):
                if str(cand) not in self.values:
                    return str(cand)
        return ""

    def values_list(self) -> list:
        return sorted(self.values)

    def to_node_selector_requirement(self) -> NodeSelectorRequirement:
        """Emit the API form (requirement.go NodeSelectorRequirement:90)."""
        if self.greater_than is not None:
            return NodeSelectorRequirement(self.key, GT, [str(self.greater_than)], self.min_values)
        if self.less_than is not None:
            return NodeSelectorRequirement(self.key, LT, [str(self.less_than)], self.min_values)
        return NodeSelectorRequirement(self.key, self.operator, sorted(self.values), self.min_values)

    def __repr__(self) -> str:
        op = self.operator
        s = f"{self.key} {op}"
        if self.values:
            vals = sorted(self.values)
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(self.values) - 5} others"]
            s += f" {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Requirement)
            and self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
            and self.min_values == other.min_values
        )

    def __hash__(self):
        return hash((self.key, self.complement, self.values, self.greater_than, self.less_than, self.min_values))


def _max_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


class Requirements(dict):
    """Key → Requirement conjunction (requirements.go:36)."""

    def __init__(self, *reqs):
        super().__init__()
        self.add(*reqs)

    def add(self, *reqs: Requirement):
        for r in reqs:
            existing = super().get(r.key)
            if existing is not None:
                r = r.intersection(existing)
            self[r.key] = r

    def copy(self) -> "Requirements":
        out = Requirements()
        dict.update(out, self)
        return out

    def get_req(self, key: str) -> Requirement:
        r = super().get(key)
        if r is None:
            return Requirement(key, EXISTS)  # undefined keys allow any value
        return r

    def has_key(self, key: str) -> bool:
        return key in self

    def merged_with(self, other: "Requirements") -> "Requirements":
        out = self.copy()
        out.add(*other.values())
        return out

    def compatible(self, incoming: "Requirements", allow_undefined=None) -> str | None:
        """One-way compatibility (requirements.go Compatible :174-187).

        Returns None when compatible, else an error string. Custom labels in
        `incoming` that we don't define are denied (unless operator NotIn /
        DoesNotExist); labels in `allow_undefined` (typically the well-known
        set) are allowed to be undefined.
        """
        allow = allow_undefined if allow_undefined is not None else frozenset()
        errs = []
        for key in incoming:
            if key in allow:
                continue
            op = incoming.get_req(key).operator
            if key in self or op in (NOT_IN, DOES_NOT_EXIST):
                continue
            errs.append(f'label "{key}" does not have known values')
        err = self.intersects(incoming)
        if err:
            errs.append(err)
        return "; ".join(errs) if errs else None

    def is_compatible(self, incoming: "Requirements", allow_undefined=None) -> bool:
        return self.compatible(incoming, allow_undefined) is None

    def intersects(self, incoming: "Requirements") -> str | None:
        """Two-way overlap over shared keys (requirements.go Intersects :282).

        Empty intersection is tolerated iff BOTH sides are NotIn/DoesNotExist.
        """
        errs = []
        small, large = (self, incoming) if len(self) <= len(incoming) else (incoming, self)
        for key in small:
            if key not in large:
                continue
            existing = self.get_req(key)
            inc = incoming.get_req(key)
            if len(existing.intersection(inc)) == 0:
                if inc.operator in (NOT_IN, DOES_NOT_EXIST) and existing.operator in (NOT_IN, DOES_NOT_EXIST):
                    continue
                errs.append(f"key {key}, {inc!r} not in {existing!r}")
        return "; ".join(errs) if errs else None

    def labels(self) -> dict:
        """Concrete labels derivable from the requirements (requirements.go
        Labels), excluding restricted node labels."""
        out = {}
        for key, r in self.items():
            if wk.is_restricted_node_label(key):
                continue
            v = r.any()
            if v:
                out[key] = v
        return out

    def keys_set(self) -> frozenset:
        return frozenset(self.keys())

    def has_min_values(self) -> bool:
        return any(r.min_values is not None for r in self.values())

    def __repr__(self) -> str:
        shown = [r for k, r in sorted(self.items()) if k not in wk.RESTRICTED_LABELS]
        return ", ".join(repr(r) for r in shown)


def from_node_selector_requirements(exprs) -> list:
    out = []
    for e in exprs or []:
        if isinstance(e, NodeSelectorRequirement):
            out.append(Requirement(e.key, e.operator, e.values, e.min_values))
        else:  # dict form
            out.append(
                Requirement(
                    e["key"], e["operator"], e.get("values", ()), e.get("minValues")
                )
            )
    return out


def node_selector_requirements(exprs) -> Requirements:
    return Requirements(*from_node_selector_requirements(exprs))


def label_requirements(labels_map: dict) -> Requirements:
    return Requirements(*[Requirement(k, IN, [v]) for k, v in (labels_map or {}).items()])


def _pod_requirements(pod, include_preferred: bool) -> Requirements:
    """requirements.go newPodRequirements:93-113: nodeSelector labels, plus
    the heaviest preferred term (when included), plus the FIRST required
    node-affinity term (outer relaxation loop drops alternatives)."""
    reqs = label_requirements(pod.node_selector)
    aff = pod.affinity
    na = aff.node_affinity if aff else None
    if na is None:
        return reqs
    if include_preferred and na.preferred:
        heaviest = sort_terms_by_weight(na.preferred)[0]
        reqs.add(*from_node_selector_requirements(heaviest.preference.match_expressions))
    if na.required:
        reqs.add(*from_node_selector_requirements(na.required[0].match_expressions))
    return reqs


def pod_requirements(pod) -> Requirements:
    return _pod_requirements(pod, include_preferred=True)


def strict_pod_requirements(pod) -> Requirements:
    return _pod_requirements(pod, include_preferred=False)


def has_preferred_node_affinity(pod) -> bool:
    return bool(
        pod
        and pod.affinity
        and pod.affinity.node_affinity
        and pod.affinity.node_affinity.preferred
    )
