from karpenter_tpu.scheduling.requirements import (  # noqa: F401
    IN,
    NOT_IN,
    EXISTS,
    DOES_NOT_EXIST,
    GT,
    LT,
    Requirement,
    Requirements,
    pod_requirements,
    strict_pod_requirements,
    label_requirements,
    node_selector_requirements,
    has_preferred_node_affinity,
)
from karpenter_tpu.scheduling.taints import Taints, KNOWN_EPHEMERAL_TAINTS  # noqa: F401
from karpenter_tpu.scheduling.hostports import HostPortUsage  # noqa: F401
from karpenter_tpu.scheduling.volumes import VolumeUsage  # noqa: F401
