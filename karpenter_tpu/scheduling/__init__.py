from karpenter_tpu.scheduling.requirements import (  # noqa: F401
    IN,
    NOT_IN,
    EXISTS,
    DOES_NOT_EXIST,
    GT,
    LT,
    Requirement,
    Requirements,
    pod_requirements,
    strict_pod_requirements,
    label_requirements,
    node_selector_requirements,
    has_preferred_node_affinity,
)
from karpenter_tpu.scheduling.taints import Taints, KNOWN_EPHEMERAL_TAINTS  # noqa: F401
from karpenter_tpu.scheduling.hostports import HostPortUsage  # noqa: F401
from karpenter_tpu.scheduling.volumes import VolumeUsage  # noqa: F401


def daemon_schedulable(template_pod, taints, requirements, allow_undefined=None) -> bool:
    """Would this daemonset pod template land on a node with the given
    taints and requirements? The single predicate behind daemon-overhead
    reservation (scheduler.go getDaemonOverhead) and the hermetic daemonset
    controller — they must agree or simulated reservations diverge from
    stamped pods."""
    if Taints(taints).tolerates(template_pod) is not None:
        return False
    return (
        requirements.compatible(
            pod_requirements(template_pod), allow_undefined=allow_undefined
        )
        is None
    )

__all__ = [
    "IN", "NOT_IN", "EXISTS", "DOES_NOT_EXIST", "GT", "LT",
    "Requirement", "Requirements", "pod_requirements",
    "strict_pod_requirements", "label_requirements",
    "node_selector_requirements", "has_preferred_node_affinity",
    "Taints", "KNOWN_EPHEMERAL_TAINTS", "HostPortUsage", "VolumeUsage",
    "daemon_schedulable",
]
